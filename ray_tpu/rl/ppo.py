"""PPO: rollout + GAE + clipped-surrogate update as one XLA program.

Capability mirror of the reference's PPO (`rllib/algorithms/ppo/ppo.py:311`
— `synchronous_parallel_sample` then `train_one_step`), redesigned so the
whole iteration is jit-compiled: `lax.scan` unrolls the vectorized env,
GAE runs as a reverse scan, and the epoch/minibatch SGD is a nested scan —
zero host↔device traffic inside an iteration.  Distributed mode fans
rollouts out to `RolloutWorker` actors and learns on the driver (the
reference's sync pattern).
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .algorithm import Algorithm
from .env import JaxEnv
from .policy import MLPPolicy


@dataclasses.dataclass
class PPOConfig:
    env: Optional[Callable[[], JaxEnv]] = None
    num_envs: int = 64            # vectorized envs per worker
    rollout_length: int = 128     # steps per env per iteration
    # bound the compiled rollout to this many envs (lax.map over
    # num_envs // env_chunk chunk rollouts); None = one flat program.
    # Use for conv/pixel policies at >=512 envs, where a single
    # proportional-to-num_envs program kills the compiler (SURVEY §9)
    env_chunk: Optional[int] = None
    num_workers: int = 0          # 0 = rollouts inline on the driver
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    entropy_coeff: float = 0.01
    vf_coeff: float = 0.5
    lr: float = 3e-4
    num_sgd_epochs: int = 4
    num_minibatches: int = 4
    max_grad_norm: float = 0.5
    hidden: tuple = (64, 64)
    seed: int = 0
    # model config dict consumed by rl.catalog (custom_model etc.);
    # None → {"hidden": hidden}
    model: Optional[dict] = None
    # agent connectors (rl.connectors, kind="obs") applied to
    # observations INSIDE the jitted rollout scan; state rides the carry
    connectors: Optional[list] = None
    # action connectors (kind="action"): transform what the env
    # receives; the stored action stays the policy output
    action_connectors: Optional[list] = None
    # reward connectors (kind="reward"): transform stored rewards
    reward_connectors: Optional[list] = None

    def build(self) -> "PPO":
        return PPO(self)


@dataclasses.dataclass
class A2CConfig(PPOConfig):
    """Synchronous advantage actor-critic (reference:
    rllib/algorithms/a2c/a2c.py:1 — A3C with synchronous updates).
    A2C IS single-epoch unclipped PPO over the whole rollout (the
    surrogate with ratio≈1 reduces to the policy gradient), so the
    preset reuses the compiled PPO iteration exactly — the same
    degenerate-case relationship the reference documents.
    """
    num_sgd_epochs: int = 1
    num_minibatches: int = 1
    clip_eps: float = 10.0        # effectively unclipped
    # build() inherited: A2C IS a PPO configuration


def _make_elementwise_apply(pipe):
    """Stateless elementwise connector application (action/reward
    pipelines) shared by the feedforward and recurrent rollouts."""
    if pipe is None or not getattr(pipe, "connectors", None):
        return lambda x: x

    def apply(x):
        for c in pipe.connectors:
            _, x = c((), x)
        return x

    return apply


def make_rollout_fn(env: JaxEnv, policy: MLPPolicy, num_envs: int,
                    rollout_length: int, pipeline=None,
                    action_pipeline=None, reward_pipeline=None,
                    env_chunk: Optional[int] = None):
    """Jittable rollout: ``(params, env_states, obs, conn_state, key) ->
    (traj, env_states, last_obs, conn_state, last_value, key)``.

    ONE implementation for every caller: with no connectors the obs
    transform is the identity and ``conn_state`` is ``()`` — zero cost
    under jit.  Obs connectors run inside the scan (the trajectory
    stores the PROCESSED observations the policy saw, so SGD log_prob
    matches) and reset per-env at episode boundaries for members marked
    ``reset_on_done``.  Action connectors transform what the ENV
    receives while the stored action stays the policy's own output
    (log_prob consistency — the reference's action-connector contract);
    reward connectors transform stored rewards.

    ``env_chunk`` is an UPPER BOUND on the compiled program's env
    batch: envs are independent, so a rollout over ``num_envs`` is
    ``lax.map`` over ``num_envs // env_chunk`` chunk-sized rollouts —
    XLA compiles ONE chunk body regardless of the env count.  When
    ``num_envs <= env_chunk`` the flat program already satisfies the
    bound and no chunking happens (so divisibility is only required
    when chunking applies).  This is the rollout twin of
    ``models/generate.py prefill_chunk`` (the round-4 compile-helper
    killer was a single program proportional to the full env batch;
    SURVEY §9 round-5 amendment)."""
    if getattr(policy, "is_recurrent", False):
        raise ValueError(
            "recurrent policies (use_lstm) are supported by PPO's local "
            "path only (make_recurrent_rollout_fn); this code path does "
            "not carry policy state")
    if env_chunk is not None and env_chunk <= 0:
        raise ValueError(f"env_chunk={env_chunk} must be positive")
    if env_chunk and env_chunk < num_envs:
        if num_envs % env_chunk:
            raise ValueError(
                f"env_chunk={env_chunk} must divide num_envs={num_envs}")
        return _make_chunked_rollout_fn(
            env, policy, num_envs, rollout_length, env_chunk,
            pipeline=pipeline, action_pipeline=action_pipeline,
            reward_pipeline=reward_pipeline)
    has_conn = pipeline is not None and pipeline.connectors
    apply_conn = jax.vmap(pipeline) if has_conn else (lambda s, x: (s, x))
    to_env_action = _make_elementwise_apply(action_pipeline)
    to_stored_reward = _make_elementwise_apply(reward_pipeline)

    def rollout(params, env_states, obs, conn_state, key):
        def step(carry, _):
            env_states, obs, conn_state, key = carry
            key, akey, skey = jax.random.split(key, 3)
            conn_state, pobs = apply_conn(conn_state, obs)
            akeys = jax.random.split(akey, num_envs)
            actions, logps, values = jax.vmap(
                lambda o, k: policy.sample_action(params, o, k))(pobs,
                                                                 akeys)
            skeys = jax.random.split(skey, num_envs)
            env_states, next_obs, rewards, dones = jax.vmap(env.step)(
                env_states, to_env_action(actions), skeys)
            if has_conn:
                conn_state = pipeline.reset_where(conn_state, dones)
            frame = {"obs": pobs, "action": actions, "logp": logps,
                     "value": values, "reward": to_stored_reward(rewards),
                     "done": dones}
            return (env_states, next_obs, conn_state, key), frame

        (env_states, last_obs, conn_state, key), traj = jax.lax.scan(
            step, (env_states, obs, conn_state, key), None,
            length=rollout_length)
        # bootstrap value on the processed view WITHOUT advancing the
        # connector state a second time for the same frame
        _, plast = apply_conn(conn_state, last_obs)
        _, last_value = jax.vmap(lambda o: policy.forward(params, o))(
            plast)
        return traj, env_states, last_obs, conn_state, last_value, key

    return rollout


def _make_chunked_rollout_fn(env, policy, num_envs, rollout_length,
                             env_chunk, pipeline=None,
                             action_pipeline=None, reward_pipeline=None):
    """``lax.map`` of chunk-sized rollouts over the env axis; same
    signature and return shapes as the flat rollout.  Params are closed
    over (one copy shared by every chunk iteration)."""
    n_chunks = num_envs // env_chunk
    inner = make_rollout_fn(env, policy, env_chunk, rollout_length,
                            pipeline=pipeline,
                            action_pipeline=action_pipeline,
                            reward_pipeline=reward_pipeline)
    tmap = jax.tree_util.tree_map

    def split(tree):           # [num_envs, ...] -> [n_chunks, chunk, ...]
        return tmap(lambda x: x.reshape((n_chunks, env_chunk)
                                        + x.shape[1:]), tree)

    def merge(tree):           # [n_chunks, chunk, ...] -> [num_envs, ...]
        return tmap(lambda x: x.reshape((num_envs,) + x.shape[2:]), tree)

    def merge_traj(tree):      # [n_chunks, T, chunk, ...] -> [T, N, ...]
        return tmap(lambda x: jnp.moveaxis(x, 0, 1).reshape(
            (rollout_length, num_envs) + x.shape[3:]), tree)

    def rollout(params, env_states, obs, conn_state, key):
        key, sub = jax.random.split(key)
        chunk_keys = jax.random.split(sub, n_chunks)

        def body(args):
            (es, ob, cs), k = args
            traj, es, last_obs, cs, last_value, _ = inner(
                params, es, ob, cs, k)
            return traj, es, last_obs, cs, last_value

        traj, env_states, last_obs, conn_state, last_value = jax.lax.map(
            body, (split((env_states, obs, conn_state)), chunk_keys))
        return (merge_traj(traj), merge(env_states), merge(last_obs),
                merge(conn_state), merge(last_value), key)

    return rollout


def make_recurrent_rollout_fn(env: JaxEnv, policy, num_envs: int,
                              rollout_length: int, pipeline=None,
                              action_pipeline=None, reward_pipeline=None):
    """Rollout for a recurrent policy: the LSTM state joins the scan
    carry and resets per env at episode boundaries.  Returns the
    SEGMENT-INITIAL state alongside the trajectory — the sequence update
    replays the recurrence from exactly there (`log_prob_seq`).
    Action/reward connector semantics match the feedforward rollout.

    → ``(params, env_states, obs, conn_state, pstate, key) -> (traj,
    env_states, last_obs, conn_state, pstate, init_pstate, last_value,
    key)``"""
    has_conn = pipeline is not None and pipeline.connectors
    apply_conn = jax.vmap(pipeline) if has_conn else (lambda s, x: (s, x))
    to_env_action = _make_elementwise_apply(action_pipeline)
    to_stored_reward = _make_elementwise_apply(reward_pipeline)

    def rollout(params, env_states, obs, conn_state, pstate, key):
        init_pstate = pstate

        def step(carry, _):
            env_states, obs, conn_state, pstate, key = carry
            key, akey, skey = jax.random.split(key, 3)
            conn_state, pobs = apply_conn(conn_state, obs)
            actions, logps, values, pstate = \
                policy.sample_action_recurrent(params, pobs, pstate, akey)
            skeys = jax.random.split(skey, num_envs)
            env_states, next_obs, rewards, dones = jax.vmap(env.step)(
                env_states, to_env_action(actions), skeys)
            if has_conn:
                conn_state = pipeline.reset_where(conn_state, dones)
            keep = (1.0 - dones.astype(jnp.float32))[..., None]
            pstate = jax.tree_util.tree_map(lambda s: s * keep, pstate)
            frame = {"obs": pobs, "action": actions, "logp": logps,
                     "value": values,
                     "reward": to_stored_reward(rewards), "done": dones}
            return (env_states, next_obs, conn_state, pstate, key), frame

        (env_states, last_obs, conn_state, pstate, key), traj = \
            jax.lax.scan(step, (env_states, obs, conn_state, pstate, key),
                         None, length=rollout_length)
        _, plast = apply_conn(conn_state, last_obs)
        _, last_value, _ = policy.step_recurrent(params, plast, pstate)
        return (traj, env_states, last_obs, conn_state, pstate,
                init_pstate, last_value, key)

    return rollout


def make_recurrent_update_fn(policy, optimizer, cfg, num_envs: int,
                             axis_name: Optional[str] = None):
    """Sequence-aware PPO update: minibatches are whole-env SEQUENCES
    (shuffling the env axis, never time), and log-probs are recomputed by
    replaying the LSTM from the segment's initial state."""
    if cfg.num_minibatches < 1:
        raise ValueError(f"num_minibatches={cfg.num_minibatches}: "
                         f"must be >= 1")
    # minibatch count = the largest divisor of num_envs not above
    # num_minibatches: every env sequence lands in exactly one minibatch
    # (a non-divisor count would silently drop whole sequences per epoch;
    # d=1 always divides, so the search cannot come up empty)
    n_mb = next(d for d in range(min(cfg.num_minibatches, num_envs),
                                 0, -1) if num_envs % d == 0)
    mb_envs = num_envs // n_mb

    def loss_fn(params, batch, init_state):
        logp, entropy, value = policy.log_prob_seq(
            params, batch["obs"], batch["action"], batch["done"],
            init_state)
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["adv"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        unclipped = ratio * adv
        clipped = jnp.clip(ratio, 1 - cfg.clip_eps,
                           1 + cfg.clip_eps) * adv
        pi_loss = -jnp.mean(jnp.minimum(unclipped, clipped))
        vf_loss = 0.5 * jnp.mean((value - batch["ret"]) ** 2)
        ent = jnp.mean(entropy)
        total = pi_loss + cfg.vf_coeff * vf_loss - cfg.entropy_coeff * ent
        return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                       "entropy": ent}

    def update_epoch(carry, _):
        params, opt_state, batch, init_state, key = carry
        key, pkey = jax.random.split(key)
        perm = jax.random.permutation(pkey, num_envs)

        def update_minibatch(carry, idx):
            params, opt_state = carry
            mb = jax.tree_util.tree_map(lambda x: x[:, idx], batch)
            mb_state = jax.tree_util.tree_map(lambda s: s[idx],
                                              init_state)
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb, mb_state)
            if axis_name is not None:
                grads = jax.lax.pmean(grads, axis_name)
            updates, opt_state = optimizer.update(grads, opt_state,
                                                  params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), aux

        idxs = perm[:n_mb * mb_envs].reshape(n_mb, mb_envs)
        (params, opt_state), auxs = jax.lax.scan(
            update_minibatch, (params, opt_state), idxs)
        return (params, opt_state, batch, init_state, key), auxs

    def update(params, opt_state, batch, init_state, key):
        (params, opt_state, _, _, key), auxs = jax.lax.scan(
            update_epoch, (params, opt_state, batch, init_state, key),
            None, length=cfg.num_sgd_epochs)
        metrics = jax.tree_util.tree_map(lambda x: x[-1, -1], auxs)
        return params, opt_state, key, metrics

    return update


def compute_gae(traj, last_value, gamma: float, lam: float):
    """Reverse-scan GAE over a [T, B] trajectory."""

    def scan_fn(carry, frame):
        next_adv, next_value = carry
        nonterminal = 1.0 - frame["done"].astype(jnp.float32)
        delta = frame["reward"] + gamma * next_value * nonterminal \
            - frame["value"]
        adv = delta + gamma * lam * nonterminal * next_adv
        return (adv, frame["value"]), adv

    (_, _), advantages = jax.lax.scan(
        scan_fn, (jnp.zeros_like(last_value), last_value), traj,
        reverse=True)
    returns = advantages + traj["value"]
    return advantages, returns


def make_update_fn(policy, optimizer, cfg, batch_size: int,
                   axis_name: Optional[str] = None):
    """Epoch/minibatch clipped-surrogate SGD as one scan program.

    With ``axis_name`` set the gradients are `pmean`-averaged across that
    mesh axis before every apply — the decentralized-DP (DDPPO) learner
    pattern where each device runs identical SGD on synchronized params.
    """
    mb_size = batch_size // cfg.num_minibatches

    def loss_fn(params, batch):
        logp, entropy, value = jax.vmap(
            lambda o, a: policy.log_prob(params, o, a))(
                batch["obs"], batch["action"])
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["adv"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        unclipped = ratio * adv
        clipped = jnp.clip(ratio, 1 - cfg.clip_eps,
                           1 + cfg.clip_eps) * adv
        pi_loss = -jnp.mean(jnp.minimum(unclipped, clipped))
        vf_loss = 0.5 * jnp.mean((value - batch["ret"]) ** 2)
        ent = jnp.mean(entropy)
        total = pi_loss + cfg.vf_coeff * vf_loss \
            - cfg.entropy_coeff * ent
        return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                       "entropy": ent}

    def update_epoch(carry, _):
        params, opt_state, batch, key = carry
        key, pkey = jax.random.split(key)
        perm = jax.random.permutation(pkey, batch_size)

        def update_minibatch(carry, idx):
            params, opt_state = carry
            mb = jax.tree_util.tree_map(lambda x: x[idx], batch)
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb)
            if axis_name is not None:
                grads = jax.lax.pmean(grads, axis_name)
            updates, opt_state = optimizer.update(
                grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), aux

        idxs = perm[:cfg.num_minibatches * mb_size].reshape(
            cfg.num_minibatches, mb_size)
        (params, opt_state), auxs = jax.lax.scan(
            update_minibatch, (params, opt_state), idxs)
        return (params, opt_state, batch, key), auxs

    def update(params, opt_state, flat, key):
        (params, opt_state, _, key), auxs = jax.lax.scan(
            update_epoch, (params, opt_state, flat, key), None,
            length=cfg.num_sgd_epochs)
        metrics = jax.tree_util.tree_map(lambda x: x[-1, -1], auxs)
        return params, opt_state, key, metrics

    return update


class PPO(Algorithm):
    _config_cls = PPOConfig

    def __init__(self, config: PPOConfig):
        super().__init__(config)
        cfg = config
        if cfg.env is None:
            raise ValueError("PPOConfig.env required (an env factory)")
        self.env = cfg.env()
        from .catalog import build_policy
        from .connectors import ConnectorPipeline
        self.pipeline = ConnectorPipeline(cfg.connectors or []) \
            .validate_kind("obs", "PPOConfig.connectors")
        self._action_pipe = ConnectorPipeline(
            cfg.action_connectors or []).validate_kind(
                "action", "PPOConfig.action_connectors")
        self._reward_pipe = ConnectorPipeline(
            cfg.reward_connectors or []).validate_kind(
                "reward", "PPOConfig.reward_connectors")
        self.policy = build_policy(
            self.env, cfg.model or {"hidden": cfg.hidden},
            obs_size_override=self.pipeline.out_size(
                self.env.observation_size))
        key = jax.random.PRNGKey(cfg.seed)
        key, pkey, ekey = jax.random.split(key, 3)
        self.params = self.policy.init(pkey)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.max_grad_norm),
            optax.adam(cfg.lr))
        self.opt_state = self.optimizer.init(self.params)
        ekeys = jax.random.split(ekey, cfg.num_envs)
        self.env_states, self.obs = jax.vmap(self.env.reset)(ekeys)
        self.key = key
        self.conn_state = self.pipeline.init_state_batch(cfg.num_envs)
        self._recurrent = bool(getattr(self.policy, "is_recurrent", False))
        if self._recurrent:
            if cfg.env_chunk:
                raise ValueError("env_chunk requires a feedforward "
                                 "policy (the LSTM state does not ride "
                                 "the chunk map)")
            self.pstate = self.policy.initial_state(cfg.num_envs)
            self._rollout = make_recurrent_rollout_fn(
                self.env, self.policy, cfg.num_envs, cfg.rollout_length,
                pipeline=self.pipeline, action_pipeline=self._action_pipe,
                reward_pipeline=self._reward_pipe)
        else:
            self._rollout = make_rollout_fn(
                self.env, self.policy, cfg.num_envs, cfg.rollout_length,
                pipeline=self.pipeline, action_pipeline=self._action_pipe,
                reward_pipeline=self._reward_pipe,
                env_chunk=cfg.env_chunk)
        self._train_iter = jax.jit(self._make_train_iter())
        self._workers = None
        if cfg.num_workers > 0:
            if self._recurrent:
                raise ValueError("use_lstm + num_workers>0 is not "
                                 "supported: rollout workers are "
                                 "feedforward-only")
            from .worker_set import WorkerSet
            self._workers = WorkerSet(cfg)
        self._init_episode_tracking(cfg.num_envs)

    # -- the compiled iteration --------------------------------------------
    def _make_update_fn(self, batch_size: int):
        return make_update_fn(self.policy, self.optimizer, self.config,
                              batch_size)

    def _make_train_iter(self):
        if self._recurrent:
            return self._make_recurrent_train_iter()
        cfg = self.config
        batch_size = cfg.num_envs * cfg.rollout_length
        update = self._make_update_fn(batch_size)

        def train_iter(params, opt_state, env_states, obs, conn_state,
                       key):
            (traj, env_states, obs, conn_state, last_value,
             key) = self._rollout(params, env_states, obs, conn_state,
                                  key)
            adv, ret = compute_gae(traj, last_value, cfg.gamma,
                                   cfg.gae_lambda)
            flat = {
                "obs": traj["obs"].reshape(batch_size, -1),
                "action": traj["action"].reshape(
                    (batch_size,) if self.env.discrete
                    else (batch_size, -1)),
                "logp": traj["logp"].reshape(batch_size),
                "adv": adv.reshape(batch_size),
                "ret": ret.reshape(batch_size),
            }
            params, opt_state, key, metrics = update(
                params, opt_state, flat, key)
            metrics["reward_sum"] = traj["reward"].sum()
            return params, opt_state, env_states, obs, conn_state, key, \
                metrics, traj["reward"], traj["done"]

        return train_iter

    def _make_recurrent_train_iter(self):
        cfg = self.config
        update = make_recurrent_update_fn(self.policy, self.optimizer,
                                          cfg, cfg.num_envs)

        def train_iter(params, opt_state, env_states, obs, conn_state,
                       pstate, key):
            (traj, env_states, obs, conn_state, pstate, init_pstate,
             last_value, key) = self._rollout(params, env_states, obs,
                                              conn_state, pstate, key)
            adv, ret = compute_gae(traj, last_value, cfg.gamma,
                                   cfg.gae_lambda)
            batch = {"obs": traj["obs"], "action": traj["action"],
                     "logp": traj["logp"], "done": traj["done"],
                     "adv": adv, "ret": ret}
            params, opt_state, key, metrics = update(
                params, opt_state, batch, init_pstate, key)
            metrics["reward_sum"] = traj["reward"].sum()
            return params, opt_state, env_states, obs, conn_state, \
                pstate, key, metrics, traj["reward"], traj["done"]

        return train_iter

    # -- Trainable interface ------------------------------------------------
    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        if self._workers is not None:
            batches = self._workers.sample(
                self.policy.get_weights(self.params))
            # learn on driver from worker trajectories
            metrics = self._learn_on_batch(batches)
            env_steps = cfg.num_workers * cfg.num_envs * cfg.rollout_length
        elif self._recurrent:
            (self.params, self.opt_state, self.env_states, self.obs,
             self.conn_state, self.pstate, self.key, metrics, rewards,
             dones) = self._train_iter(
                self.params, self.opt_state, self.env_states, self.obs,
                self.conn_state, self.pstate, self.key)
            env_steps = cfg.num_envs * cfg.rollout_length
            self._track_episodes(np.asarray(rewards), np.asarray(dones))
            metrics = {k: float(v) for k, v in metrics.items()}
        else:
            (self.params, self.opt_state, self.env_states, self.obs,
             self.conn_state, self.key, metrics, rewards,
             dones) = self._train_iter(
                self.params, self.opt_state, self.env_states, self.obs,
                self.conn_state, self.key)
            env_steps = cfg.num_envs * cfg.rollout_length
            self._track_episodes(np.asarray(rewards), np.asarray(dones))
            metrics = {k: float(v) for k, v in metrics.items()}
        dt = time.perf_counter() - t0
        out = dict(metrics)
        out.update({
            "env_steps_this_iter": env_steps,
            "env_steps_per_s": env_steps / dt,
            "episode_reward_mean": self.episode_reward_mean(),
        })
        return out

    def _learn_on_batch(self, batches) -> Dict[str, float]:
        keys = ("obs", "action", "logp", "adv", "ret")
        flat = {k: jnp.asarray(np.concatenate([b[k] for b in batches]))
                for k in keys}
        for b in batches:
            ep = b.get("episode_returns")
            if ep is not None and len(ep):
                self._ep_done_returns.extend(np.asarray(ep).tolist())
        total = flat["obs"].shape[0]
        if getattr(self, "_update_bs", None) != total:
            self._update_bs = total
            self._update_jit = jax.jit(self._make_update_fn(total))
        self.params, self.opt_state, self.key, metrics = self._update_jit(
            self.params, self.opt_state, flat, self.key)
        return {k: float(v) for k, v in metrics.items()}

    # -- checkpointing ------------------------------------------------------
    def get_state(self) -> Dict[str, Any]:
        # connector state ships with the policy (reference: connectors
        # are checkpointed with it) — a restored ObsNormalizer without
        # its moments would feed the policy unnormalized obs
        return {"params": self.policy.get_weights(self.params),
                "conn_state": jax.tree_util.tree_map(
                    np.asarray, self.conn_state),
                "iteration": self.iteration}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = self.policy.set_weights(self.params, state["params"])
        if state.get("conn_state") is not None:
            self.conn_state = jax.tree_util.tree_map(
                jnp.asarray, state["conn_state"])
        self.iteration = state.get("iteration", 0)
