"""TD3 / DDPG: deterministic-policy continuous control, fully jitted.

Capability mirror of the reference's DDPG family
(`rllib/algorithms/ddpg/ddpg.py:1` — deterministic actor, Q critic,
OU/Gaussian exploration noise) and its TD3 preset
(`rllib/algorithms/td3/td3.py:1` — twin critics, target-policy
smoothing, delayed policy updates).  Redesigned like sac.py: the replay
buffer lives on device (replay.py) and one ``training_step`` (collect
scan → critic/delayed-actor update scan) is a single XLA program; the
delayed update is a ``lax.cond`` on the update counter instead of the
reference's host-side ``policy_delay`` loop bookkeeping.

``DDPGConfig`` is TD3 with the three TD3 tricks off (single critic, no
smoothing, every-step policy updates) and OU noise — the reference's
relationship between the two algorithms, inverted (there TD3 subclasses
DDPG).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import replay
from .algorithm import Algorithm
from .env import JaxEnv
from .exploration import GaussianActionNoise, OrnsteinUhlenbeckNoise
from .policy import mlp_apply, mlp_init as _mlp_init


def _relu_mlp(params, x):
    return mlp_apply(params, x, activation=jax.nn.relu)


@dataclasses.dataclass
class TD3Config:
    env: Optional[Callable[[], JaxEnv]] = None
    num_envs: int = 16
    rollout_steps: int = 16
    buffer_capacity: int = 100_000
    batch_size: int = 256
    num_updates: int = 16
    gamma: float = 0.99
    actor_lr: float = 3e-4
    critic_lr: float = 3e-4
    tau: float = 0.005             # Polyak target-average rate
    policy_delay: int = 2          # critic updates per actor update
    smooth_target_policy: bool = True
    target_noise: float = 0.2      # smoothing noise stddev
    noise_clip: float = 0.5        # smoothing noise clip
    twin_q: bool = True
    ou_noise: bool = False         # exploration: OU instead of Gaussian
    expl_noise_scale: float = 0.1  # Gaussian exploration stddev (start)
    expl_noise_final: float = 0.05
    expl_decay_steps: int = 50_000
    prioritized_replay: bool = False
    per_alpha: float = 0.6
    per_beta: float = 0.4
    learn_start: int = 1_000
    hidden: tuple = (128, 128)
    seed: int = 0

    def build(self) -> "TD3":
        return TD3(self)


@dataclasses.dataclass
class DDPGConfig(TD3Config):
    """Vanilla DDPG: the TD3 tricks off, OU exploration on."""
    policy_delay: int = 1
    smooth_target_policy: bool = False
    twin_q: bool = False
    ou_noise: bool = True


class TD3(Algorithm):
    _config_cls = TD3Config

    def __init__(self, config: TD3Config):
        super().__init__(config)
        cfg = config
        if cfg.env is None:
            raise ValueError("TD3Config.env required (an env factory)")
        self.env = cfg.env()
        if self.env.discrete:
            raise ValueError("TD3/DDPG requires a continuous-action env")
        obs_dim = self.env.observation_size
        act_dim = self.env.action_size
        self.act_dim = act_dim
        key = jax.random.PRNGKey(cfg.seed)
        key, k1, k2, k3, ekey = jax.random.split(key, 5)
        h = tuple(cfg.hidden)
        # q2 exists even with twin_q=False (uniform pytree shapes keep
        # one compiled program per config); it never enters the loss
        # there, so its grads are zero and it stays at init
        self.params = {
            "actor": _mlp_init(k1, (obs_dim,) + h + (act_dim,)),
            "q1": _mlp_init(k2, (obs_dim + act_dim,) + h + (1,)),
            "q2": _mlp_init(k3, (obs_dim + act_dim,) + h + (1,)),
        }
        self.targets = jax.tree_util.tree_map(lambda x: x, self.params)
        self.actor_opt = optax.adam(cfg.actor_lr)
        self.critic_opt = optax.adam(cfg.critic_lr)
        self.actor_opt_state = self.actor_opt.init(self.params["actor"])
        self.critic_opt_state = self.critic_opt.init(
            {"q1": self.params["q1"], "q2": self.params["q2"]})
        if cfg.ou_noise:
            self.noise = OrnsteinUhlenbeckNoise(
                act_dim, clip=self.env.action_high)
            self.noise_state = jnp.zeros((cfg.num_envs, act_dim))
        else:
            self.noise = GaussianActionNoise(
                cfg.expl_noise_scale * self.env.action_high,
                cfg.expl_noise_final * self.env.action_high,
                cfg.expl_decay_steps, clip=self.env.action_high)
            self.noise_state = ()
        ekeys = jax.random.split(ekey, cfg.num_envs)
        self.env_states, self.obs = jax.vmap(self.env.reset)(ekeys)
        self._replay_ops = replay.make_ops(
            cfg.prioritized_replay, alpha=cfg.per_alpha, beta=cfg.per_beta)
        buffer_init = self._replay_ops[0]
        self.buffer = buffer_init(cfg.buffer_capacity, {
            "obs": jnp.zeros((obs_dim,), jnp.float32),
            "action": jnp.zeros((act_dim,), jnp.float32),
            "reward": jnp.zeros((), jnp.float32),
            "next_obs": jnp.zeros((obs_dim,), jnp.float32),
            "done": jnp.zeros((), jnp.float32),
        })
        self.key = key
        self._update_count = jnp.zeros((), jnp.int32)
        self._train_iter = jax.jit(self._make_train_iter())
        self._init_episode_tracking(cfg.num_envs)

    # -- policy -------------------------------------------------------------
    def _act(self, actor_params, obs):
        return self.env.action_high * jnp.tanh(
            _relu_mlp(actor_params, obs))

    def _q(self, q_params, obs, act):
        return _relu_mlp(q_params, jnp.concatenate([obs, act],
                                                   axis=-1))[..., 0]

    # -- the compiled iteration --------------------------------------------
    def _make_update_block(self):
        """``num_updates`` TD3 updates behind the learn-start gate —
        shared by the fused collect+update iteration and external-input
        learners (ApexDDPG), like dqn.py's `_make_update_block`."""
        cfg = self.config
        high = self.env.action_high
        _, _, sample_fn, update_pri = self._replay_ops

        def critic_loss_fn(qp, targets, batch, weights, key):
            next_a = self._act(targets["actor"], batch["next_obs"])
            if cfg.smooth_target_policy:
                eps = jnp.clip(
                    cfg.target_noise * jax.random.normal(
                        key, next_a.shape),
                    -cfg.noise_clip, cfg.noise_clip)
                next_a = jnp.clip(next_a + eps, -high, high)
            tq1 = self._q(targets["q1"], batch["next_obs"], next_a)
            if cfg.twin_q:
                tq = jnp.minimum(tq1, self._q(
                    targets["q2"], batch["next_obs"], next_a))
            else:
                tq = tq1
            target = jax.lax.stop_gradient(
                batch["reward"] + cfg.gamma * (1.0 - batch["done"])
                * tq)
            td1 = self._q(qp["q1"], batch["obs"], batch["action"]) \
                - target
            loss = jnp.mean(weights * td1 ** 2)
            td_abs = jnp.abs(td1)
            if cfg.twin_q:
                td2 = self._q(qp["q2"], batch["obs"],
                              batch["action"]) - target
                loss = loss + jnp.mean(weights * td2 ** 2)
                td_abs = 0.5 * (td_abs + jnp.abs(td2))
            return loss, td_abs

        def actor_loss_fn(ap, q1, batch):
            a = self._act(ap, batch["obs"])
            return -jnp.mean(self._q(q1, batch["obs"], a))

        def update(carry, _):
            (params, targets, aopt_state, copt_state, buffer, key,
             upd_count) = carry
            batch, idx, weights, key = sample_fn(buffer, key,
                                                 cfg.batch_size)
            key, skey = jax.random.split(key)
            qp = {"q1": params["q1"], "q2": params["q2"]}
            (_, td_abs), qgrads = jax.value_and_grad(
                critic_loss_fn, has_aux=True)(qp, targets, batch,
                                              weights, skey)
            buffer = update_pri(buffer, idx, td_abs)
            qupd, copt_state = self.critic_opt.update(
                qgrads, copt_state, qp)
            qp = optax.apply_updates(qp, qupd)
            params = {**params, "q1": qp["q1"], "q2": qp["q2"]}

            def do_actor(args):
                params, targets, aopt_state = args
                agrads = jax.grad(actor_loss_fn)(
                    params["actor"], params["q1"], batch)
                aupd, aopt_state = self.actor_opt.update(
                    agrads, aopt_state, params["actor"])
                actor = optax.apply_updates(params["actor"], aupd)
                params = {**params, "actor": actor}
                # targets track ONLY on actor-update steps (TD3's
                # delayed-target rule; delay=1 makes it every step)
                targets = jax.tree_util.tree_map(
                    lambda t, p: (1 - cfg.tau) * t + cfg.tau * p,
                    targets, params)
                return params, targets, aopt_state

            params, targets, aopt_state = jax.lax.cond(
                upd_count % cfg.policy_delay == 0,
                do_actor, lambda args: args,
                (params, targets, aopt_state))
            return (params, targets, aopt_state, copt_state, buffer,
                    key, upd_count + 1), td_abs.mean()

        def update_block(params, targets, aopt_state, copt_state,
                         buffer, key, upd_count):
            do_learn = buffer["size"] >= cfg.learn_start

            def run(args):
                (params, targets, aopt_state, copt_state, buffer, key,
                 upd_count) = args
                (params, targets, aopt_state, copt_state, buffer, key,
                 upd_count), tds = jax.lax.scan(
                    update, args, None, length=cfg.num_updates)
                return (params, targets, aopt_state, copt_state, buffer,
                        key, upd_count, tds[-1])

            def skip(args):
                return args + (jnp.zeros(()),)

            return jax.lax.cond(
                do_learn, run, skip,
                (params, targets, aopt_state, copt_state, buffer, key,
                 upd_count))

        return update_block

    def _make_train_iter(self):
        cfg = self.config
        env = self.env
        noise = self.noise
        _, add_fn, _, _ = self._replay_ops
        update_block = self._make_update_block()

        def train_iter(params, targets, aopt_state, copt_state, buffer,
                       env_states, obs, noise_state, key, upd_count,
                       total_steps):

            def collect(carry, _):
                buffer, env_states, obs, noise_state, key = carry
                key, nkey, skey = jax.random.split(key, 3)
                action = self._act(params["actor"], obs)
                noise_state, action = noise(noise_state, nkey, action,
                                            total_steps)
                skeys = jax.random.split(skey, cfg.num_envs)
                env_states, next_obs, reward, done = jax.vmap(env.step)(
                    env_states, action, skeys)
                buffer = add_fn(buffer, {
                    "obs": obs.astype(jnp.float32),
                    "action": action.astype(jnp.float32),
                    "reward": reward.astype(jnp.float32),
                    "next_obs": next_obs.astype(jnp.float32),
                    "done": done.astype(jnp.float32),
                }, cfg.num_envs)
                return (buffer, env_states, next_obs, noise_state, key), \
                    {"reward": reward, "done": done}

            (buffer, env_states, obs, noise_state, key), traj = \
                jax.lax.scan(collect,
                             (buffer, env_states, obs, noise_state, key),
                             None, length=cfg.rollout_steps)

            (params, targets, aopt_state, copt_state, buffer, key,
             upd_count, last_td) = update_block(
                params, targets, aopt_state, copt_state, buffer, key,
                upd_count)
            metrics = {"td_abs": last_td, "buffer_size": buffer["size"]}
            return (params, targets, aopt_state, copt_state, buffer,
                    env_states, obs, noise_state, key, upd_count,
                    metrics, traj["reward"], traj["done"])

        return train_iter

    # -- Trainable interface ------------------------------------------------
    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        (self.params, self.targets, self.actor_opt_state,
         self.critic_opt_state, self.buffer, self.env_states, self.obs,
         self.noise_state, self.key, self._update_count, metrics,
         rewards, dones) = self._train_iter(
            self.params, self.targets, self.actor_opt_state,
            self.critic_opt_state, self.buffer, self.env_states,
            self.obs, self.noise_state, self.key, self._update_count,
            jnp.asarray(self._total_env_steps, jnp.float32))
        env_steps = cfg.num_envs * cfg.rollout_steps
        self._track_episodes(np.asarray(rewards), np.asarray(dones))
        dt = time.perf_counter() - t0
        out = {k: float(v) for k, v in metrics.items()}
        out["step_reward_mean"] = float(np.asarray(rewards).mean())
        out.update({
            "env_steps_this_iter": env_steps,
            "env_steps_per_s": env_steps / dt,
            "episode_reward_mean": self.episode_reward_mean(),
        })
        return out

    def action_fn(self):
        """Deterministic jittable policy for deployment/eval."""
        act, params = self._act, self.params

        def policy(obs, key):
            return act(params["actor"], obs)
        return policy

    # -- checkpointing ------------------------------------------------------
    def get_state(self) -> Dict[str, Any]:
        to_np = lambda t: jax.tree_util.tree_map(np.asarray, t)  # noqa: E731
        return {"params": to_np(self.params),
                "targets": to_np(self.targets),
                "iteration": self.iteration,
                # exploration noise anneals on env_steps_total and the
                # policy_delay phase rides the update counter: a restored
                # run must not restart either (cf. dqn.py get_state)
                "env_steps_total": self._total_env_steps,
                "update_count": int(self._update_count)}

    def set_state(self, state: Dict[str, Any]) -> None:
        to_dev = lambda t, w: jax.tree_util.tree_map(  # noqa: E731
            lambda _, x: jnp.asarray(x), t, w)
        self.params = to_dev(self.params, state["params"])
        self.targets = to_dev(self.targets, state["targets"])
        self.iteration = state.get("iteration", 0)
        self._total_env_steps = state.get("env_steps_total", 0)
        self._update_count = jnp.asarray(
            state.get("update_count", 0), jnp.int32)


class DDPG(TD3):
    _config_cls = DDPGConfig
