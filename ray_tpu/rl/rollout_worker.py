"""Rollout workers: distributed experience collection.

Capability mirror of the reference's `RolloutWorker.sample`
(`rllib/evaluation/rollout_worker.py:153,864`): an actor owning env +
policy; the driver broadcasts weights and gathers sample batches.  The
inner loop is the same jitted rollout as the single-process path — an
actor on a TPU host samples at compiled speed.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


class RolloutWorker:
    def __init__(self, config_blob: bytes, worker_index: int):
        import jax

        from ..core.serialization import loads_function
        from .catalog import build_policy
        from .connectors import ConnectorPipeline
        from .ppo import compute_gae, make_rollout_fn
        cfg = loads_function(config_blob)
        self.cfg = cfg
        self.env = cfg.env()
        # SAME model + connector wiring as the driver-side algorithm —
        # a worker with a raw MLP while the driver trains a catalog
        # model (or processed obs) would diverge or crash on weights
        pipeline = ConnectorPipeline(
            getattr(cfg, "connectors", None) or [])
        action_pipe = ConnectorPipeline(
            getattr(cfg, "action_connectors", None) or [])
        reward_pipe = ConnectorPipeline(
            getattr(cfg, "reward_connectors", None) or [])
        self.policy = build_policy(
            self.env, getattr(cfg, "model", None) or
            {"hidden": cfg.hidden},
            obs_size_override=pipeline.out_size(
                self.env.observation_size))
        key = jax.random.PRNGKey(cfg.seed + 1000 * (worker_index + 1))
        self.key, ekey, pkey = jax.random.split(key, 3)
        self.params = self.policy.init(pkey)
        ekeys = jax.random.split(ekey, cfg.num_envs)
        self.env_states, self.obs = jax.vmap(self.env.reset)(ekeys)
        self.conn_state = pipeline.init_state_batch(cfg.num_envs)
        rollout = make_rollout_fn(self.env, self.policy, cfg.num_envs,
                                  cfg.rollout_length, pipeline=pipeline,
                                  action_pipeline=action_pipe,
                                  reward_pipeline=reward_pipe,
                                  env_chunk=cfg.env_chunk)

        def sample_fn(params, env_states, obs, conn_state, key):
            traj, env_states, obs, conn_state, last_value, key = rollout(
                params, env_states, obs, conn_state, key)
            adv, ret = compute_gae(traj, last_value, cfg.gamma,
                                   cfg.gae_lambda)
            bs = cfg.num_envs * cfg.rollout_length
            flat = {
                "obs": traj["obs"].reshape(bs, -1),
                "action": traj["action"].reshape(
                    (bs,) if self.env.discrete else (bs, -1)),
                "logp": traj["logp"].reshape(bs),
                "adv": adv.reshape(bs),
                "ret": ret.reshape(bs),
            }
            return flat, env_states, obs, conn_state, key, \
                traj["reward"], traj["done"]

        self._sample = jax.jit(sample_fn)
        self._ep_returns = np.zeros(cfg.num_envs)
        self._done_returns: list = []

    def sample(self, weights) -> Dict[str, Any]:
        self.params = self.policy.set_weights(self.params, weights)
        (flat, self.env_states, self.obs, self.conn_state, self.key,
         rewards, dones) = self._sample(
            self.params, self.env_states, self.obs, self.conn_state,
            self.key)
        rewards, dones = np.asarray(rewards), np.asarray(dones)
        for t in range(rewards.shape[0]):
            self._ep_returns += rewards[t]
            f = dones[t].astype(bool)
            if f.any():
                self._done_returns.extend(self._ep_returns[f].tolist())
                self._ep_returns[f] = 0.0
        batch = {k: np.asarray(v) for k, v in flat.items()}
        batch["episode_returns"] = np.asarray(self._done_returns[-100:])
        return batch
