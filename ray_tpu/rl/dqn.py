"""DQN: value-based RL with a device-resident replay buffer.

Capability mirror of the reference's DQN family
(`rllib/algorithms/dqn/dqn.py` — replay buffer, target network, double-Q,
epsilon-greedy exploration) — redesigned so one `training_step` compiles
to ONE XLA program: `lax.scan` collects `rollout_steps` vectorized env
transitions straight into the on-device circular buffer (replay.py), then
a second scan runs `num_updates` double-DQN SGD steps on uniform samples,
with a Polyak-averaged target network.  No host↔device traffic inside an
iteration — the same design constraint as ppo.py.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import replay
from .algorithm import Algorithm
from .env import JaxEnv
from .policy import mlp_apply, mlp_init


class QNetwork:
    """MLP state-action value network: obs → Q[action].

    With ``dueling`` the torso feeds separate value and advantage heads
    and Q = V + A - mean(A) (reference: dueling architecture,
    `rllib/algorithms/dqn` dueling option).  With ``num_atoms > 1`` the
    net is DISTRIBUTIONAL (C51, reference: `rllib/algorithms/dqn`
    num_atoms option): ``logits`` returns [.., A, atoms] and ``apply``
    still returns expected Q-values, so every value-based call site
    (exploration, greedy eval, double-DQN selection) works unchanged.
    """

    def __init__(self, obs_size: int, n_actions: int,
                 hidden=(64, 64), dueling: bool = False,
                 num_atoms: int = 1, v_min: float = -10.0,
                 v_max: float = 10.0):
        self.obs_size = obs_size
        self.n_actions = n_actions
        self.hidden = tuple(hidden)
        self.dueling = dueling
        self.num_atoms = num_atoms
        if num_atoms > 1:
            if not v_min < v_max:
                raise ValueError(
                    f"distributional support needs v_min < v_max "
                    f"(got {v_min} >= {v_max}): a zero-width support "
                    f"divides by zero in the projection")
            self.support = jnp.linspace(v_min, v_max, num_atoms)

    def init(self, key: jax.Array):
        if self.num_atoms > 1 and not self.dueling:
            return mlp_init(key, (self.obs_size,) + self.hidden
                            + (self.n_actions * self.num_atoms,))
        if not self.dueling:
            return mlp_init(
                key, (self.obs_size,) + self.hidden + (self.n_actions,))
        if not self.hidden:
            raise ValueError("dueling=True needs at least one hidden "
                             "layer (the shared torso the V/A heads read)")
        kt, kv, ka = jax.random.split(key, 3)
        width = self.hidden[-1]
        # dueling heads; with num_atoms > 1 each head emits atoms-wide
        # outputs (the Rainbow dueling-distributional structure)
        return {"torso": mlp_init(kt, (self.obs_size,) + self.hidden),
                "v": mlp_init(kv, (width, self.num_atoms)),
                "a": mlp_init(ka, (width,
                                   self.n_actions * self.num_atoms))}

    def _torso(self, params, obs: jnp.ndarray) -> jnp.ndarray:
        x = obs
        for layer in params["torso"]:    # activation on EVERY torso layer
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        return x

    def logits(self, params, obs: jnp.ndarray) -> jnp.ndarray:
        """[.., A, atoms] distribution logits (num_atoms > 1 only)."""
        if self.dueling:
            x = self._torso(params, obs)
            v = mlp_apply(params["v"], x)[..., None, :]   # [.., 1, atoms]
            a = mlp_apply(params["a"], x).reshape(
                x.shape[:-1] + (self.n_actions, self.num_atoms))
            return v + a - a.mean(axis=-2, keepdims=True)
        out = mlp_apply(params, obs)
        return out.reshape(out.shape[:-1]
                           + (self.n_actions, self.num_atoms))

    def apply(self, params, obs: jnp.ndarray) -> jnp.ndarray:
        if self.num_atoms > 1:
            probs = jax.nn.softmax(self.logits(params, obs), axis=-1)
            return (probs * self.support).sum(axis=-1)
        if not self.dueling:
            return mlp_apply(params, obs)
        x = self._torso(params, obs)
        v = mlp_apply(params["v"], x)                      # [..., 1]
        a = mlp_apply(params["a"], x)                      # [..., A]
        return v + a - a.mean(axis=-1, keepdims=True)


def categorical_td_loss(q: "QNetwork", params, target_params, batch,
                        weights, double_q: bool):
    """C51 (Bellemare et al. 2017): project the Bellman-shifted target
    distribution onto the fixed support, cross-entropy against the
    predicted distribution at the taken action.  Handles per-sample
    gamma (n-step) like the scalar path.  → (loss, per-sample CE) —
    the CE doubles as the PER priority, the distributional
    convention."""
    z = q.support                                        # [atoms]
    atoms = q.num_atoms
    dz = (z[-1] - z[0]) / (atoms - 1)
    # next-state distribution at the selected action
    next_logits = q.logits(target_params, batch["next_obs"])
    if double_q:
        next_a = jnp.argmax(q.apply(params, batch["next_obs"]),
                            axis=-1)
    else:
        next_probs_all = jax.nn.softmax(next_logits, axis=-1)
        next_a = jnp.argmax((next_probs_all * z).sum(-1), axis=-1)
    next_p = jax.nn.softmax(jnp.take_along_axis(
        next_logits, next_a[:, None, None].repeat(atoms, -1),
        axis=1)[:, 0], axis=-1)                          # [B, atoms]
    # Bellman shift + clamp + triangular projection onto the support
    tz = jnp.clip(batch["reward"][:, None]
                  + batch["gamma_n"][:, None]
                  * (1.0 - batch["done"][:, None]) * z[None, :],
                  z[0], z[-1])                           # [B, atoms]
    b = (tz - z[0]) / dz
    low = jnp.clip(jnp.floor(b), 0, atoms - 1)
    up = jnp.clip(jnp.ceil(b), 0, atoms - 1)
    # when low == up (b integral) all mass goes to that atom
    w_up = jnp.where(up == low, 1.0, b - low)
    w_low = 1.0 - w_up
    proj = jnp.zeros_like(next_p)
    bidx = jnp.arange(next_p.shape[0])[:, None]
    proj = proj.at[bidx, low.astype(jnp.int32)].add(next_p * w_low)
    proj = proj.at[bidx, up.astype(jnp.int32)].add(next_p * w_up)
    proj = jax.lax.stop_gradient(proj)
    pred_logits = jnp.take_along_axis(
        q.logits(params, batch["obs"]),
        batch["action"][:, None, None].repeat(atoms, -1),
        axis=1)[:, 0]                                    # [B, atoms]
    log_p = jax.nn.log_softmax(pred_logits, axis=-1)
    ce = -(proj * log_p).sum(axis=-1)                    # [B]
    return jnp.mean(weights * ce), ce


def dqn_target(q_apply, params, target_params, reward, next_obs, done,
               gamma, double_q: bool):
    """The (double-)DQN TD target, stop-gradiented — ONE definition
    shared by online DQN and offline CQL so target-selection fixes
    cannot diverge.  ``gamma`` may be a scalar or a per-sample vector
    (n-step)."""
    next_qt = q_apply(target_params, next_obs)
    if double_q:
        # double-DQN: online net selects, target net evaluates
        next_a = jnp.argmax(q_apply(params, next_obs), axis=-1)
        next_q = jnp.take_along_axis(next_qt, next_a[:, None],
                                     axis=-1)[:, 0]
    else:
        next_q = jnp.max(next_qt, axis=-1)
    return jax.lax.stop_gradient(reward + gamma * next_q * (1.0 - done))


@dataclasses.dataclass
class DQNConfig:
    env: Optional[Callable[[], JaxEnv]] = None
    num_envs: int = 16
    rollout_steps: int = 32        # env steps per env per iteration
    buffer_capacity: int = 50_000
    batch_size: int = 128
    num_updates: int = 32          # SGD steps per iteration
    gamma: float = 0.99
    lr: float = 1e-3
    tau: float = 0.01              # Polyak target-average rate
    double_q: bool = True
    dueling: bool = False          # V + A - mean(A) heads
    num_atoms: int = 1             # >1: distributional C51 over
    v_min: float = -10.0           #   linspace(v_min, v_max, atoms)
    v_max: float = 10.0
    n_step: int = 1                # n-step targets (window gathered at
    #   sample time from buffer adjacency; cursor-crossing windows fall
    #   back to 1-step)
    prioritized_replay: bool = False
    per_alpha: float = 0.6         # priority exponent
    per_beta: float = 0.4          # initial importance-weight exponent
    per_beta_final: float = 1.0    # annealed to over eps_decay_steps
    #   (PER paper: bias correction becomes exact as training converges)
    eps_start: float = 1.0
    eps_end: float = 0.05
    eps_decay_steps: int = 20_000  # env steps to anneal epsilon over
    learn_start: int = 1_000       # env steps before updates begin
    hidden: tuple = (64, 64)
    seed: int = 0
    # -- external input (reference: rllib/env/policy_server_input.py) ------
    # transitions arrive from out-of-process simulators via an attached
    # input reader (rl/external.py PolicyServerInput) instead of the
    # fused env-collect scan; spaces are declared since there is no env
    external_input: bool = False
    observation_size: Optional[int] = None   # required when env is None
    num_actions: Optional[int] = None        # required when env is None
    ingest_chunk: int = 64         # fixed insert size (one compiled shape)

    def build(self) -> "DQN":
        return DQN(self)


class DQN(Algorithm):
    _config_cls = DQNConfig

    def __init__(self, config: DQNConfig):
        super().__init__(config)
        cfg = config
        if cfg.external_input:
            if cfg.n_step > 1:
                raise ValueError(
                    "external_input does not support n_step > 1: the "
                    "n-step window reads buffer ADJACENCY, and external "
                    "transitions interleave arbitrarily many episodes")
            if cfg.env is not None:
                env = cfg.env()
                if not env.discrete:
                    raise ValueError("DQN requires a discrete-action "
                                     "env (action_size of a continuous "
                                     "env is a dimension, not a count)")
                obs_dim, n_act = env.observation_size, env.action_size
            elif cfg.observation_size and cfg.num_actions:
                obs_dim, n_act = cfg.observation_size, cfg.num_actions
            else:
                raise ValueError(
                    "external_input needs observation_size + num_actions "
                    "(or an env factory to borrow the spaces from)")
            self.env = None
        else:
            if cfg.env is None:
                raise ValueError("DQNConfig.env required (an env factory)")
            self.env = cfg.env()
            if not self.env.discrete:
                raise ValueError("DQN requires a discrete-action env")
            obs_dim, n_act = (self.env.observation_size,
                              self.env.action_size)
        if cfg.n_step > 1 and (cfg.n_step - 1) * cfg.num_envs >= \
                cfg.buffer_capacity:
            raise ValueError(
                f"n_step={cfg.n_step} with num_envs={cfg.num_envs} needs "
                f"a window of {(cfg.n_step - 1) * cfg.num_envs} slots, "
                f">= buffer_capacity={cfg.buffer_capacity}: every sample "
                f"would silently fall back to 1-step targets")
        self.n_actions = n_act
        self.q = QNetwork(obs_dim, n_act,
                          hidden=cfg.hidden, dueling=cfg.dueling,
                          num_atoms=cfg.num_atoms, v_min=cfg.v_min,
                          v_max=cfg.v_max)
        key = jax.random.PRNGKey(cfg.seed)
        key, pkey, ekey = jax.random.split(key, 3)
        self.params = self.q.init(pkey)
        self.target_params = jax.tree_util.tree_map(lambda x: x,
                                                    self.params)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._replay_ops = replay.make_ops(
            cfg.prioritized_replay, alpha=cfg.per_alpha, beta=cfg.per_beta)
        buffer_init = self._replay_ops[0]
        self.buffer = buffer_init(cfg.buffer_capacity, {
            "obs": jnp.zeros((obs_dim,), jnp.float32),
            "action": jnp.zeros((), jnp.int32),
            "reward": jnp.zeros((), jnp.float32),
            "next_obs": jnp.zeros((obs_dim,), jnp.float32),
            "done": jnp.zeros((), jnp.float32),
        })
        self.key = key
        from .exploration import EpsilonGreedy
        self._explorer = EpsilonGreedy(cfg.eps_start, cfg.eps_end,
                                       cfg.eps_decay_steps)
        self._act_jit = jax.jit(
            lambda p, o: jnp.argmax(self.q.apply(p, o), axis=-1))
        self._np_rng = np.random.default_rng(cfg.seed)
        if cfg.external_input:
            _, add_fn, _, _ = self._replay_ops
            self._ingest_jit = jax.jit(
                lambda buf, batch: add_fn(buf, batch, cfg.ingest_chunk))
            self._update_jit = jax.jit(
                self._make_update_block(insert_stride=1))
            self._staging: list = []
            self._input_reader = None
        else:
            ekeys = jax.random.split(ekey, cfg.num_envs)
            self.env_states, self.obs = jax.vmap(self.env.reset)(ekeys)
            self._train_iter = jax.jit(self._make_train_iter())
        self._init_episode_tracking(cfg.num_envs)

    # -- the compiled iteration --------------------------------------------
    def _make_update_block(self, insert_stride: int):
        """``num_updates`` TD steps on replay samples behind the
        learn_start gate — shared by the fused env-collect iteration and
        the external-input path, where collection happens out of
        process.  ``insert_stride``: slot distance between temporally
        adjacent transitions (num_envs for the vectorized collect scan,
        1 for external ingestion)."""
        cfg, q, opt = self.config, self.q, self.optimizer
        _, _, sample_fn, update_pri = self._replay_ops

        def td_loss(params, target_params, batch, weights):
            if cfg.num_atoms > 1:
                # C51: cross-entropy against the projected target
                # distribution; per-sample CE is the PER priority
                return categorical_td_loss(q, params, target_params,
                                           batch, weights,
                                           cfg.double_q)
            qvals = q.apply(params, batch["obs"])
            q_sa = jnp.take_along_axis(
                qvals, batch["action"][:, None], axis=-1)[:, 0]
            target = dqn_target(q.apply, params, target_params,
                                batch["reward"], batch["next_obs"],
                                batch["done"], batch["gamma_n"],
                                cfg.double_q)
            td = q_sa - target
            return jnp.mean(weights * td ** 2), jnp.abs(td)

        def update_block(params, target_params, opt_state, buffer, key,
                         total_steps):
            # anneal the PER bias-correction exponent toward its final
            # value on the same horizon as epsilon
            frac = jnp.clip(total_steps / cfg.eps_decay_steps, 0.0, 1.0)
            beta_now = cfg.per_beta + \
                (cfg.per_beta_final - cfg.per_beta) * frac

            def update(carry, _):
                params, target_params, opt_state, buffer, key = carry
                batch, idx, weights, key = sample_fn(
                    buffer, key, cfg.batch_size, beta_now=beta_now)
                if cfg.n_step > 1:
                    # collection interleaves insert_stride slots per step
                    reward_n, next_obs_n, done_n, gamma_n = \
                        replay.nstep_window(buffer, idx, cfg.n_step,
                                            cfg.gamma,
                                            stride=insert_stride,
                                            one_step=batch)
                    batch = {**batch, "reward": reward_n,
                             "next_obs": next_obs_n, "done": done_n,
                             "gamma_n": gamma_n}
                else:
                    batch = {**batch,
                             "gamma_n": jnp.full((cfg.batch_size,),
                                                 cfg.gamma)}
                (loss, td_abs), grads = jax.value_and_grad(
                    td_loss, has_aux=True)(params, target_params, batch,
                                           weights)
                buffer = update_pri(buffer, idx, td_abs)
                updates, opt_state = opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                target_params = jax.tree_util.tree_map(
                    lambda t, p: (1 - cfg.tau) * t + cfg.tau * p,
                    target_params, params)
                return (params, target_params, opt_state, buffer,
                        key), loss

            # gate learning until the buffer has learn_start transitions
            do_learn = buffer["size"] >= cfg.learn_start

            def run_updates(args):
                params, target_params, opt_state, buffer, key = args
                (params, target_params, opt_state, buffer, key), losses = \
                    jax.lax.scan(update,
                                 (params, target_params, opt_state,
                                  buffer, key),
                                 None, length=cfg.num_updates)
                return (params, target_params, opt_state, buffer, key,
                        losses[-1])

            def skip_updates(args):
                params, target_params, opt_state, buffer, key = args
                return (params, target_params, opt_state, buffer, key,
                        jnp.zeros(()))

            return jax.lax.cond(
                do_learn, run_updates, skip_updates,
                (params, target_params, opt_state, buffer, key))

        return update_block

    def _make_train_iter(self):
        cfg = self.config
        env, q = self.env, self.q
        _, add_fn, _, _ = self._replay_ops
        insert_bs = cfg.num_envs  # one buffer insert per scanned env step
        update_block = self._make_update_block(insert_stride=cfg.num_envs)
        explorer = self._explorer

        def train_iter(params, target_params, opt_state, buffer,
                       env_states, obs, key, total_steps):

            def collect(carry, _):
                buffer, env_states, obs, key = carry
                key, akey, skey = jax.random.split(key, 3)
                qvals = q.apply(params, obs)                  # [B, A]
                _, action = explorer((), akey, qvals, total_steps)
                skeys = jax.random.split(skey, cfg.num_envs)
                env_states, next_obs, reward, done = jax.vmap(env.step)(
                    env_states, action, skeys)
                buffer = add_fn(buffer, {
                    "obs": obs.astype(jnp.float32),
                    "action": action.astype(jnp.int32),
                    "reward": reward.astype(jnp.float32),
                    "next_obs": next_obs.astype(jnp.float32),
                    "done": done.astype(jnp.float32),
                }, insert_bs)
                frame = {"reward": reward, "done": done}
                return (buffer, env_states, next_obs, key), frame

            (buffer, env_states, obs, key), traj = jax.lax.scan(
                collect, (buffer, env_states, obs, key), None,
                length=cfg.rollout_steps)

            (params, target_params, opt_state, buffer, key,
             last_loss) = update_block(params, target_params, opt_state,
                                       buffer, key, total_steps)
            metrics = {"td_loss": last_loss,
                       "epsilon": explorer.epsilon(total_steps),
                       "buffer_size": buffer["size"]}
            return (params, target_params, opt_state, buffer, env_states,
                    obs, key, metrics, traj["reward"], traj["done"])

        return train_iter

    # -- external input (reference: policy_server_input.py) -----------------
    def set_input_reader(self, reader: Any) -> None:
        """Attach a transition source (rl/external.py
        PolicyServerInput): ``poll_transitions() -> list[dict]`` and
        ``poll_episode_returns() -> list[float]``."""
        if not self.config.external_input:
            raise ValueError("build with external_input=True first")
        self._input_reader = reader

    def compute_single_action(self, obs, explore: bool = True) -> int:
        """Epsilon-greedy action for ONE observation — the
        policy-serving entry point (reference: Algorithm
        .compute_single_action).  Exploration anneals on the
        transitions-seen counter like the compiled collect scan."""
        cfg = self.config
        if explore:
            # the SAME schedule object the compiled collect scan uses —
            # served-action exploration must not diverge from in-process
            eps = float(self._explorer.epsilon(self._total_env_steps))
            if self._np_rng.random() < eps:
                return int(self._np_rng.integers(self.n_actions))
        obs = jnp.asarray(np.asarray(obs, np.float32))[None]
        return int(self._act_jit(self.params, obs)[0])

    def _external_training_step(self) -> Dict[str, Any]:
        cfg = self.config
        if self._input_reader is None:
            raise RuntimeError(
                "external_input=True but no input reader attached — "
                "call set_input_reader(PolicyServerInput(...))")
        t0 = time.perf_counter()
        trans = self._input_reader.poll_transitions()
        self._staging.extend(trans)
        inserted = 0
        while len(self._staging) >= cfg.ingest_chunk:
            part = self._staging[:cfg.ingest_chunk]
            del self._staging[:cfg.ingest_chunk]
            batch = {
                "obs": jnp.asarray(np.stack(
                    [t["obs"] for t in part]).astype(np.float32)),
                "action": jnp.asarray(np.asarray(
                    [t["action"] for t in part], np.int32)),
                "reward": jnp.asarray(np.asarray(
                    [t["reward"] for t in part], np.float32)),
                "next_obs": jnp.asarray(np.stack(
                    [t["next_obs"] for t in part]).astype(np.float32)),
                "done": jnp.asarray(np.asarray(
                    [t["done"] for t in part], np.float32)),
            }
            self.buffer = self._ingest_jit(self.buffer, batch)
            inserted += cfg.ingest_chunk
        (self.params, self.target_params, self.opt_state, self.buffer,
         self.key, last_loss) = self._update_jit(
            self.params, self.target_params, self.opt_state, self.buffer,
            self.key, jnp.asarray(self._total_env_steps, jnp.float32))
        self._ep_done_returns.extend(
            self._input_reader.poll_episode_returns())
        dt = time.perf_counter() - t0
        return {
            "td_loss": float(last_loss),
            "buffer_size": int(self.buffer["size"]),
            "transitions_received": len(trans),
            "transitions_inserted": inserted,
            "env_steps_this_iter": len(trans),
            "env_steps_per_s": len(trans) / dt,
            "episode_reward_mean": self.episode_reward_mean(),
        }

    # -- Trainable interface ------------------------------------------------
    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        if cfg.external_input:
            return self._external_training_step()
        t0 = time.perf_counter()
        (self.params, self.target_params, self.opt_state, self.buffer,
         self.env_states, self.obs, self.key, metrics, rewards, dones) = \
            self._train_iter(self.params, self.target_params,
                             self.opt_state, self.buffer, self.env_states,
                             self.obs, self.key,
                             jnp.asarray(self._total_env_steps, jnp.float32))
        env_steps = cfg.num_envs * cfg.rollout_steps
        self._track_episodes(np.asarray(rewards), np.asarray(dones))
        dt = time.perf_counter() - t0
        out = {k: float(v) for k, v in metrics.items()}
        out["step_reward_mean"] = float(np.asarray(rewards).mean())
        out.update({
            "env_steps_this_iter": env_steps,
            "env_steps_per_s": env_steps / dt,
            "episode_reward_mean": self.episode_reward_mean(),
        })
        return out

    # -- checkpointing ------------------------------------------------------
    def get_state(self) -> Dict[str, Any]:
        to_np = lambda t: jax.tree_util.tree_map(np.asarray, t)  # noqa: E731
        return {"params": to_np(self.params),
                "target_params": to_np(self.target_params),
                "iteration": self.iteration,
                # epsilon anneals on env_steps_total: a restored run must
                # not restart exploration from eps_start
                "env_steps_total": self._total_env_steps}

    def set_state(self, state: Dict[str, Any]) -> None:
        to_dev = lambda t, w: jax.tree_util.tree_map(  # noqa: E731
            lambda _, x: jnp.asarray(x), t, w)
        self.params = to_dev(self.params, state["params"])
        self.target_params = to_dev(self.target_params,
                                    state["target_params"])
        self.iteration = state.get("iteration", 0)
        self._total_env_steps = state.get("env_steps_total", 0)


@dataclasses.dataclass
class SimpleQConfig(DQNConfig):
    """The reference's SimpleQ (`rllib/algorithms/simple_q/simple_q.py`):
    DQN stripped to its 2013 core — no double-Q, no dueling heads, no
    n-step, uniform replay.  A preset, because here those are all config
    bits of the one compiled DQN iteration."""
    double_q: bool = False
    dueling: bool = False
    n_step: int = 1
    prioritized_replay: bool = False
    num_atoms: int = 1

    def build(self) -> "SimpleQ":  # type: ignore[override]
        return SimpleQ(self)


class SimpleQ(DQN):
    _config_cls = SimpleQConfig


@dataclasses.dataclass
class RainbowConfig(DQNConfig):
    """Every DQN improvement at once (the Rainbow recipe, which the
    reference exposes as DQN config flags: `rllib/algorithms/dqn/dqn.py`
    n_step/double/dueling/noisy/num_atoms): double-Q + dueling + 3-step
    + prioritized replay + C51 distributional heads."""
    double_q: bool = True
    dueling: bool = True
    n_step: int = 3
    prioritized_replay: bool = True
    num_atoms: int = 51
    v_min: float = -10.0
    v_max: float = 10.0

    def build(self) -> "Rainbow":  # type: ignore[override]
        return Rainbow(self)


class Rainbow(DQN):
    _config_cls = RainbowConfig
