"""Decision Transformer: offline RL as return-conditioned sequence
modeling.

Capability mirror of the reference's DT
(`rllib/algorithms/dt/dt.py` — GPT-style causal transformer over
(return-to-go, state, action) triplets, trained with action-prediction
loss on offline trajectories, deployed by conditioning on a target
return).  TPU-first shape: the trunk is a compact causal transformer
built on the framework's own attention op (`ops/attention.py` — the same
flash kernel the LM stack uses when shapes allow), training samples
fixed-length windows so one jitted epoch covers permuted minibatches
like BC/CQL/CRR, and evaluation unrolls the feedback loop as a
``lax.scan`` whose carry is the rolling (rtg, obs, act) context —
data-dependent Python control flow nowhere.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..ops.attention import multi_head_attention
from .algorithm import Algorithm
from .env import JaxEnv



def _dense_init(key, d_in, d_out, scale=None):
    scale = scale if scale is not None else math.sqrt(2.0 / d_in)
    return {"w": jax.random.normal(key, (d_in, d_out)) * scale,
            "b": jnp.zeros((d_out,))}


def _dense(p, x):
    return x @ p["w"] + p["b"]


def trunk_init(key, d_model: int, n_layers: int, n_heads: int,
               d_ff: int):
    def layer(k):
        k1, k2, k3, k4 = jax.random.split(k, 4)
        return {
            "ln1": {"g": jnp.ones((d_model,)), "b": jnp.zeros((d_model,))},
            "qkv": _dense_init(k1, d_model, 3 * d_model),
            "proj": _dense_init(k2, d_model, d_model,
                                scale=0.02 / math.sqrt(n_layers)),
            "ln2": {"g": jnp.ones((d_model,)), "b": jnp.zeros((d_model,))},
            "up": _dense_init(k3, d_model, d_ff),
            "down": _dense_init(k4, d_ff, d_model,
                                scale=0.02 / math.sqrt(n_layers)),
        }

    keys = jax.random.split(key, n_layers)
    return {"layers": jax.vmap(layer)(keys),
            "ln_f": {"g": jnp.ones((d_model,)),
                     "b": jnp.zeros((d_model,))}}


def _ln(p, x):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-5) * p["g"] + p["b"]


def trunk_apply(params, x: jnp.ndarray, n_heads: int) -> jnp.ndarray:
    """[B, S, D] → [B, S, D], causal; layers scanned (stacked weights,
    the same compile-once shape as models/transformer.py)."""
    b, s, d = x.shape
    hd = d // n_heads

    def layer_fn(h, lp):
        y = _ln(lp["ln1"], h)
        qkv = _dense(lp["qkv"], y).reshape(b, s, 3, n_heads, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        att = multi_head_attention(q, k, v, causal=True)
        h = h + _dense(lp["proj"], att.reshape(b, s, d))
        y = _ln(lp["ln2"], h)
        h = h + _dense(lp["down"], jax.nn.gelu(_dense(lp["up"], y)))
        return h, None

    x, _ = jax.lax.scan(layer_fn, x, params["layers"])
    return _ln(params["ln_f"], x)


@dataclasses.dataclass
class DTConfig:
    env: Optional[Callable[[], JaxEnv]] = None
    dataset: Optional[Dict[str, np.ndarray]] = None   # EPISODIC columns
    context_len: int = 20          # K triplets of (rtg, obs, act)
    d_model: int = 64
    n_layers: int = 2
    n_heads: int = 4
    d_ff: int = 256
    gamma: float = 1.0             # DT uses undiscounted returns-to-go
    lr: float = 1e-3
    batch_size: int = 64
    steps_per_iter: int = 100      # minibatch updates per train()
    target_return: float = 200.0   # conditioning return at eval time
    rtg_scale: float = 100.0       # return normalization divisor
    seed: int = 0

    def build(self) -> "DT":
        return DT(self)


def _returns_to_go(rewards: np.ndarray, gamma: float) -> np.ndarray:
    """Per-episode (discounted) returns-to-go; gamma=1 (the DT paper's
    convention) is a plain reverse cumsum."""
    if gamma >= 1.0:
        return np.flip(np.cumsum(np.flip(rewards))).copy()
    out = np.empty_like(rewards, dtype=np.float64)
    acc = 0.0
    for t in range(len(rewards) - 1, -1, -1):
        acc = rewards[t] + gamma * acc
        out[t] = acc
    return out.astype(rewards.dtype)


def episodes_from_columns(ds: Dict[str, np.ndarray]):
    """Split columnar (obs, action, reward, done) rows into episode
    lists — offline datasets store flat transition columns
    (rl/offline.py collect_dataset).  Episodes end at ``done`` marks
    AND at ``env_id`` changes (when present): each env's trailing
    partial episode carries done=0, so without the env_id cut it would
    be spliced onto the next env's first episode."""
    done = np.asarray(ds["done"]) > 0.5
    n = len(done)
    last = np.zeros(n, bool)
    if "env_id" in ds:
        env_id = np.asarray(ds["env_id"])
        last[:-1] = env_id[1:] != env_id[:-1]
        last[-1] = True
    else:
        last[-1] = True
    ends = np.flatnonzero(done | last)
    episodes = []
    start = 0
    for e in ends:
        sl = slice(start, e + 1)
        episodes.append({k: np.asarray(ds[k][sl]) for k in
                         ("obs", "action", "reward")})
        start = e + 1
    return episodes


class DT(Algorithm):
    _config_cls = DTConfig

    def __init__(self, config: DTConfig):
        super().__init__(config)
        cfg = config
        if cfg.env is None or cfg.dataset is None:
            raise ValueError("DTConfig.env and DTConfig.dataset required")
        if cfg.d_model % cfg.n_heads:
            raise ValueError(f"d_model={cfg.d_model} not divisible by "
                             f"n_heads={cfg.n_heads}")
        self.env = cfg.env()
        if not self.env.discrete:
            raise ValueError("this DT implementation is discrete-action "
                             "(continuous heads are an MSE swap)")
        obs_dim, n_act = self.env.observation_size, self.env.action_size
        self.n_actions = n_act
        K, D = cfg.context_len, cfg.d_model
        key = jax.random.PRNGKey(cfg.seed)
        (key, kt, kr, ko, ka, kh, kp) = jax.random.split(key, 7)
        self.params = {
            "trunk": trunk_init(kt, D, cfg.n_layers, cfg.n_heads,
                                cfg.d_ff),
            "emb_rtg": _dense_init(kr, 1, D),
            "emb_obs": _dense_init(ko, obs_dim, D),
            "emb_act": _dense_init(ka, n_act, D),
            "emb_t": jax.random.normal(kh, (K, D)) * 0.02,
            "head": _dense_init(kp, D, n_act, scale=0.02),
        }
        self.optimizer = optax.adamw(cfg.lr, weight_decay=1e-4)
        self.opt_state = self.optimizer.init(self.params)
        self.key = key

        # ---- window the offline episodes once, on the host ---------------
        episodes = episodes_from_columns(cfg.dataset)
        obs_w, act_w, rtg_w, len_w = [], [], [], []
        for ep in episodes:
            T = len(ep["reward"])
            rtg = _returns_to_go(ep["reward"], cfg.gamma)
            for start in range(0, T, max(1, K // 2)):
                end = min(start + K, T)
                n = end - start
                pad = K - n
                obs_w.append(np.pad(ep["obs"][start:end].astype(
                    np.float32), ((0, pad), (0, 0))))
                act_w.append(np.pad(ep["action"][start:end].astype(
                    np.int64), (0, pad)))
                rtg_w.append(np.pad(rtg[start:end].astype(np.float32),
                                    (0, pad)))
                len_w.append(n)
        self._windows = {
            "obs": jnp.asarray(np.stack(obs_w)),          # [W, K, obs]
            "action": jnp.asarray(np.stack(act_w), jnp.int32),
            "rtg": jnp.asarray(np.stack(rtg_w)) / cfg.rtg_scale,
            "mask": jnp.asarray(
                np.arange(K)[None, :] < np.asarray(len_w)[:, None],
                jnp.float32),
        }
        self._update = jax.jit(self._make_update())
        self._eval_rollout = jax.jit(self._make_eval_rollout())

    # -- the model: windows → per-step action logits ------------------------
    def _logits(self, params, rtg, obs, act):
        """[B, K] rtg, [B, K, obs] obs, [B, K] act → [B, K, A] logits
        predicting act_t from (.., rtg_t, s_t)."""
        cfg = self.config
        B, K = rtg.shape
        e_r = _dense(params["emb_rtg"], rtg[..., None])
        e_s = _dense(params["emb_obs"], obs)
        a_onehot = jax.nn.one_hot(act, self.n_actions)
        e_a = _dense(params["emb_act"], a_onehot)
        t_emb = params["emb_t"][None, :K]
        # interleave [r_0, s_0, a_0, r_1, s_1, a_1, ...] → [B, 3K, D]
        tokens = jnp.stack([e_r + t_emb, e_s + t_emb, e_a + t_emb],
                           axis=2).reshape(B, 3 * K, cfg.d_model)
        h = trunk_apply(params["trunk"], tokens, cfg.n_heads)
        # the state token (position 3t+1) predicts action a_t
        h_s = h[:, 1::3]
        return _dense(params["head"], h_s)

    def _make_update(self):
        """Windows enter as a jit ARGUMENT, not a closure: a closed-over
        dataset would be baked into the executable as XLA constants
        (a second device copy + compile time growing with the data)."""
        cfg = self.config
        W = self._windows["obs"].shape[0]

        def loss_fn(params, batch):
            logits = self._logits(params, batch["rtg"], batch["obs"],
                                  batch["action"])
            logp = jax.nn.log_softmax(logits)
            ce = -jnp.take_along_axis(
                logp, batch["action"][..., None], axis=-1)[..., 0]
            return (ce * batch["mask"]).sum() / batch["mask"].sum()

        def update(params, opt_state, key, windows):
            def step(carry, _):
                params, opt_state, key = carry
                key, bkey = jax.random.split(key)
                idx = jax.random.randint(bkey, (cfg.batch_size,), 0, W)
                batch = jax.tree_util.tree_map(lambda x: x[idx],
                                               windows)
                loss, grads = jax.value_and_grad(loss_fn)(params, batch)
                updates, opt_state = self.optimizer.update(
                    grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state, key), loss

            (params, opt_state, key), losses = jax.lax.scan(
                step, (params, opt_state, key), None,
                length=cfg.steps_per_iter)
            return params, opt_state, key, losses.mean()

        return update

    # -- return-conditioned evaluation --------------------------------------
    def _make_eval_rollout(self):
        cfg, env = self.config, self.env
        K = cfg.context_len
        horizon = env.max_episode_steps

        def rollout(params, key, target_return):
            key, rkey = jax.random.split(key)
            state, obs = env.reset(rkey)
            obs_dim = obs.shape[-1]
            ctx = {
                "rtg": jnp.zeros((K,)),
                "obs": jnp.zeros((K, obs_dim)),
                "act": jnp.zeros((K,), jnp.int32),
                "n": jnp.zeros((), jnp.int32),   # filled positions
            }

            def place(buf, x, n):
                """Left-aligned insert: fill slot n while the window is
                filling, shift once full — matching the TRAINING window
                layout (content left-aligned, padding only at the end),
                so eval never shows the model leading-zero contexts it
                was never trained on."""
                shifted = jnp.concatenate([buf[1:], x[None]], axis=0)
                filled = jax.lax.dynamic_update_index_in_dim(
                    buf, x, jnp.minimum(n, K - 1), axis=0)
                return jnp.where(n < K, filled, shifted)

            def step(carry, _):
                state, obs, ctx, rtg_now, ret, done, key = carry
                n = ctx["n"]
                pos = jnp.minimum(n, K - 1)   # slot holding the current step
                # place the CURRENT (rtg, obs) with a placeholder action,
                # predict that slot's action
                ctx2 = {
                    "rtg": place(ctx["rtg"], rtg_now / cfg.rtg_scale, n),
                    "obs": place(ctx["obs"], obs, n),
                    "act": place(ctx["act"], jnp.zeros((), jnp.int32), n),
                }
                logits = self._logits(
                    params, ctx2["rtg"][None], ctx2["obs"][None],
                    ctx2["act"][None])[0, pos]
                action = jnp.argmax(logits, -1)
                key, skey = jax.random.split(key)
                state2, obs2, reward, step_done = env.step(state, action,
                                                           skey)
                # write the TAKEN action into the context
                ctx = {"rtg": ctx2["rtg"], "obs": ctx2["obs"],
                       "act": jax.lax.dynamic_update_index_in_dim(
                           ctx2["act"], action, pos, axis=0),
                       "n": jnp.minimum(n + 1, K)}
                live = 1.0 - done
                ret = ret + reward * live
                rtg_next = rtg_now - reward
                done = jnp.maximum(done, step_done.astype(jnp.float32))
                return (state2, obs2, ctx, rtg_next, ret, done,
                        key), None

            init = (state, obs, ctx, jnp.asarray(target_return,
                                                 jnp.float32),
                    jnp.zeros(()), jnp.zeros(()), key)
            (_, _, _, _, ret, _, _), _ = jax.lax.scan(
                step, init, None, length=horizon)
            return ret

        return rollout

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        self.params, self.opt_state, self.key, loss = self._update(
            self.params, self.opt_state, self.key, self._windows)
        dt_s = time.perf_counter() - t0
        return {"action_ce_loss": float(loss),
                "windows": int(self._windows["obs"].shape[0]),
                "updates_per_s": cfg.steps_per_iter / dt_s,
                "env_steps_this_iter": 0}

    def evaluate(self, n_episodes: int = 8,
                 target_return: Optional[float] = None) -> float:
        """Mean achieved return when conditioned on ``target_return``."""
        tr = target_return if target_return is not None \
            else self.config.target_return
        rets = []
        for i in range(n_episodes):
            self.key, ekey = jax.random.split(self.key)
            rets.append(float(self._eval_rollout(self.params, ekey, tr)))
        return float(np.mean(rets))

    # -- checkpointing -------------------------------------------------------
    def get_state(self) -> Dict[str, Any]:
        to_np = lambda t: jax.tree_util.tree_map(np.asarray, t)  # noqa: E731
        return {"params": to_np(self.params),
                "iteration": self.iteration}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = jax.tree_util.tree_map(
            lambda _, x: jnp.asarray(x), self.params, state["params"])
        self.iteration = state.get("iteration", 0)
