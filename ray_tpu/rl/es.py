"""Evolution strategies: derivative-free RL by cluster-wide fan-out.

Capability mirror of the reference's ES/ARS family
(`rllib/algorithms/es/es.py` — perturb the policy, evaluate episodes in
parallel workers, estimate the gradient from ranked returns).  The shape
that makes ES interesting here is the RUNTIME's: each iteration fans one
task per perturbation pair across the cluster (tasks, not actors — ES
evaluation is stateless), ships only a SEED per task (workers regenerate
the noise locally, the classic bandwidth trick), and the jitted
evaluator runs the whole episode batch as one XLA program.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .algorithm import Algorithm
from .env import JaxEnv
from .policy import mlp_apply, mlp_init


def _flatten(params):
    leaves, treedef = jax.tree_util.tree_flatten(params)
    flat = jnp.concatenate([jnp.ravel(x) for x in leaves])
    shapes = [x.shape for x in leaves]
    sizes = [int(np.prod(s)) for s in shapes]
    return flat, (treedef, shapes, sizes)


def _unflatten(flat, meta):
    treedef, shapes, sizes = meta
    out, off = [], 0
    for shape, size in zip(shapes, sizes):
        out.append(flat[off:off + size].reshape(shape))
        off += size
    return jax.tree_util.tree_unflatten(treedef, out)


def make_eval_fn(env: JaxEnv, n_episodes: int, horizon: int):
    """Jittable: (params, key) → mean undiscounted return of
    ``n_episodes`` vectorized episodes under the DETERMINISTIC policy."""

    def evaluate(params, key):
        ekeys = jax.random.split(key, n_episodes)
        states, obs = jax.vmap(env.reset)(ekeys)

        def step(carry, _):
            states, obs, ret, done, key = carry
            out = jax.vmap(lambda o: mlp_apply(params, o))(obs)
            if env.discrete:
                action = jnp.argmax(out, axis=-1)
            else:
                action = env.action_high * jnp.tanh(out)
            key, skey = jax.random.split(key)
            skeys = jax.random.split(skey, n_episodes)
            states, obs, reward, step_done = jax.vmap(env.step)(
                states, action, skeys)
            ret = ret + reward * (1.0 - done)
            done = jnp.maximum(done, step_done.astype(jnp.float32))
            return (states, obs, ret, done, key), None

        init = (states, obs, jnp.zeros(n_episodes),
                jnp.zeros(n_episodes), key)
        (_, _, ret, _, _), _ = jax.lax.scan(step, init, None,
                                            length=horizon)
        return ret.mean()

    return evaluate


@dataclasses.dataclass
class ESConfig:
    env: Optional[Callable[[], JaxEnv]] = None
    num_perturbations: int = 16    # antithetic PAIRS per iteration
    sigma: float = 0.1             # perturbation stddev
    lr: float = 0.05
    episodes_per_eval: int = 4
    horizon: int = 200
    num_workers: int = 0           # 0 = evaluate inline on the driver
    hidden: tuple = (32, 32)
    seed: int = 0

    def build(self) -> "ES":
        return ES(self)


#: one compiled evaluator per (env factory CONTENT, episodes, horizon)
#: per process; keyed by a cloudpickle hash (a deserialized factory is a
#: fresh object per task, so identity keys would never hit, and closures
#: with equal qualnames but different captures must not collide) and
#: bounded (FIFO) so exotic factories cannot grow it without limit
_EVAL_CACHE: dict = {}
_EVAL_CACHE_MAX = 8


def _cached_eval(env_factory, episodes, horizon):
    import hashlib

    import cloudpickle
    # content hash: identical factories (including captured closure
    # values) share a compiled evaluator; make(5) and make(10) do not
    key = (hashlib.sha256(cloudpickle.dumps(env_factory)).hexdigest(),
           episodes, horizon)
    fn = _EVAL_CACHE.get(key)
    if fn is None:
        fn = _EVAL_CACHE[key] = jax.jit(
            make_eval_fn(env_factory(), episodes, horizon))
        while len(_EVAL_CACHE) > _EVAL_CACHE_MAX:
            _EVAL_CACHE.pop(next(iter(_EVAL_CACHE)))
    return fn


def _es_eval_task(env_factory, episodes, horizon, flat_np, meta,
                  sigma, noise_seed):
    """One perturbation pair, runnable as a cluster task: regenerate the
    noise from its seed sequence, evaluate +eps and -eps."""
    evaluate = _cached_eval(env_factory, episodes, horizon)
    base = jnp.asarray(flat_np)
    seq = np.random.SeedSequence(noise_seed)
    rng = np.random.default_rng(seq)
    eps = jnp.asarray(rng.standard_normal(base.shape[0], dtype=np.float32))
    # fold the FULL seed sequence into the episode keys: every
    # (config seed, iteration, index) triple sees fresh episodes
    eval_key = jax.random.PRNGKey(int(seq.generate_state(1)[0]))
    r_pos = float(evaluate(_unflatten(base + sigma * eps, meta), eval_key))
    r_neg = float(evaluate(_unflatten(base - sigma * eps, meta), eval_key))
    return r_pos, r_neg


class ES(Algorithm):
    _config_cls = ESConfig

    def __init__(self, config: ESConfig):
        super().__init__(config)
        cfg = config
        if cfg.env is None:
            raise ValueError("ESConfig.env required (an env factory)")
        self.env = cfg.env()
        n_out = self.env.action_size
        key = jax.random.PRNGKey(cfg.seed)
        params = mlp_init(key, (self.env.observation_size,)
                          + tuple(cfg.hidden) + (n_out,))
        self.flat, self.meta = _flatten(params)
        self._eval = jax.jit(make_eval_fn(self.env,
                                          cfg.episodes_per_eval,
                                          cfg.horizon))
        self._iter_seed = cfg.seed
        self._remote_task = None

    # -- shared perturbation fan-out (ES and ARS) ---------------------------
    def _evaluate_directions(self):
        """Advance the noise stream one iteration and evaluate every
        antithetic perturbation pair — one task per pair across the
        cluster when ``num_workers > 0``, inline otherwise.
        → (seeds, r_pos, r_neg)."""
        cfg = self.config
        self._iter_seed += 1
        # SeedSequence entropy lists mix (config seed, iteration, index)
        # NON-linearly: adjacent config seeds must not share noise streams
        seeds = [[cfg.seed, self._iter_seed, i]
                 for i in range(cfg.num_perturbations)]
        flat_np = np.asarray(self.flat)  # one device->host transfer

        if cfg.num_workers > 0:
            import ray_tpu
            flat_ref = ray_tpu.put(flat_np)
            if self._remote_task is None:  # register the task once
                self._remote_task = ray_tpu.remote(_es_eval_task)
            refs = [self._remote_task.remote(
                        cfg.env, cfg.episodes_per_eval, cfg.horizon,
                        flat_ref, self.meta, cfg.sigma, s)
                    for s in seeds]
            results = ray_tpu.get(refs, timeout=600.0)
        else:
            results = [_es_eval_task(cfg.env, cfg.episodes_per_eval,
                                     cfg.horizon, flat_np, self.meta,
                                     cfg.sigma, s)
                       for s in seeds]

        r_pos = np.asarray([r[0] for r in results])
        r_neg = np.asarray([r[1] for r in results])
        return seeds, r_pos, r_neg

    # -- one ES iteration ---------------------------------------------------
    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        seeds, r_pos, r_neg = self._evaluate_directions()
        # centered-rank normalization over the 2n evaluations (the
        # public ES recipe: robust to return scale)
        all_r = np.concatenate([r_pos, r_neg])
        ranks = np.empty_like(all_r)
        ranks[np.argsort(all_r)] = np.arange(all_r.size)
        ranks = ranks / (all_r.size - 1) - 0.5
        w = ranks[:len(r_pos)] - ranks[len(r_pos):]

        grad = np.zeros(self.flat.shape[0], dtype=np.float32)
        for wi, s in zip(w, seeds):
            rng = np.random.default_rng(np.random.SeedSequence(s))
            grad += wi * rng.standard_normal(self.flat.shape[0],
                                             dtype=np.float32)
        grad /= (len(seeds) * cfg.sigma)
        self.flat = self.flat + cfg.lr * jnp.asarray(grad)

        dt = time.perf_counter() - t0
        episodes = 2 * len(seeds) * cfg.episodes_per_eval
        mean_return = float(self._eval(
            _unflatten(self.flat, self.meta),
            jax.random.PRNGKey(self._iter_seed)))

        return {"episode_reward_mean": mean_return,
                "perturbations": len(seeds),
                "env_steps_this_iter": episodes * cfg.horizon,
                "env_steps_per_s": episodes * cfg.horizon / dt}

    # -- checkpointing ------------------------------------------------------
    def get_state(self) -> Dict[str, Any]:
        return {"flat": np.asarray(self.flat),
                "iteration": self.iteration}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.flat = jnp.asarray(state["flat"])
        self.iteration = state.get("iteration", 0)
        # resume the noise stream where it left off — replaying seeds
        # already trained on would break the gradient estimate's
        # independence assumption
        self._iter_seed = self.config.seed + self.iteration


# ---------------------------------------------------------------------------
# ARS: Augmented Random Search (the reference's `rllib/algorithms/ars/
# ars.py` — same perturbation fan-out as ES with three changes from the
# public ARS recipe: only the top-k directions by max(r+, r-) contribute,
# the step is normalized by the std of the selected returns, and the
# perturbation noise is NOT rank-normalized).  Shares the ES evaluation
# tasks (seed-only shipping, cluster fan-out, jitted episode batches).
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ARSConfig(ESConfig):
    top_k: int = 0                 # 0 → use all directions (vanilla BRS)
    sigma: float = 0.05
    lr: float = 0.02

    def build(self) -> "ARS":      # type: ignore[override]
        return ARS(self)


class ARS(ES):
    _config_cls = ARSConfig

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        seeds, r_pos, r_neg = self._evaluate_directions()
        # top-k directions by best-of-pair return (ARS v1-t / v2-t)
        k = cfg.top_k or len(seeds)
        k = min(k, len(seeds))
        order = np.argsort(-np.maximum(r_pos, r_neg))[:k]
        used = np.concatenate([r_pos[order], r_neg[order]])
        sigma_r = float(used.std()) or 1.0

        grad = np.zeros(self.flat.shape[0], dtype=np.float32)
        for i in order:
            rng = np.random.default_rng(np.random.SeedSequence(seeds[i]))
            eps = rng.standard_normal(self.flat.shape[0], dtype=np.float32)
            grad += (r_pos[i] - r_neg[i]) * eps
        self.flat = self.flat + (cfg.lr / (k * sigma_r)) * jnp.asarray(grad)

        dt = time.perf_counter() - t0
        episodes = 2 * len(seeds) * cfg.episodes_per_eval
        mean_return = float(self._eval(
            _unflatten(self.flat, self.meta),
            jax.random.PRNGKey(self._iter_seed)))
        return {"episode_reward_mean": mean_return,
                "perturbations": len(seeds), "top_k": k,
                "return_std": sigma_r,
                "env_steps_this_iter": episodes * cfg.horizon,
                "env_steps_per_s": episodes * cfg.horizon / dt}
