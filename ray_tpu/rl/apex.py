"""Ape-X DQN: distributed prioritized replay (Horgan et al. 2018).

Capability mirror of the reference's APEX
(`rllib/algorithms/apex_dqn/apex_dqn.py:1` — many actors with a
SPECTRUM of fixed exploration rates feed one prioritized-replay
learner).  TPU-first composition: the learner IS the external-input
DQN (device-resident buffer + compiled update scan, dqn.py
`_make_update_block`), and each collector actor runs its own compiled
epsilon-greedy rollout scan — the IMPALA async driver pattern (one
in-flight collect per actor, re-armed with fresh weights as each batch
lands) feeding the DQN replay path instead of V-trace."""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .algorithm import track_episode_returns
from .dqn import DQN, DQNConfig, QNetwork
from .td3 import TD3, TD3Config


def collector_epsilon(i: int, n: int, base: float = 0.4,
                      alpha: float = 7.0) -> float:
    """The Ape-X exploration spectrum: eps_i = base^(1 + i*alpha/(n-1))
    — worker 0 explores most, the tail is near-greedy."""
    if n <= 1:
        return base
    return float(base ** (1.0 + i * alpha / (n - 1)))


class _CollectorBase:
    """Shared collector-actor scaffolding: compiled vectorized rollout
    scan + columnar shipping.  Subclasses implement `_setup(cfg,
    worker_index, num_workers, pkey)` (build nets from the param key,
    set ``self.params``) and `_action_fn(params, obs, key)` (the
    per-step exploration rule)."""

    def __init__(self, config_blob: bytes, worker_index: int,
                 num_workers: int):
        from ..core.serialization import loads_function
        cfg = loads_function(config_blob)
        self.cfg = cfg
        self.env = cfg.env()
        key = jax.random.PRNGKey(cfg.seed + 104729 * (worker_index + 1))
        self.key, ekey, pkey = jax.random.split(key, 3)
        self._setup(cfg, worker_index, num_workers, pkey)
        ekeys = jax.random.split(ekey, cfg.num_envs)
        self.env_states, self.obs = jax.vmap(self.env.reset)(ekeys)
        self._collect = jax.jit(self._make_collect())
        self._ep_returns = np.zeros(cfg.num_envs)
        self._done_returns: list = []

    def _setup(self, cfg, worker_index, num_workers, pkey):
        raise NotImplementedError

    def _action_fn(self, params, obs, key):
        raise NotImplementedError

    def _make_collect(self):
        cfg, env = self.cfg, self.env

        def collect(params, env_states, obs, key):
            def step(carry, _):
                env_states, obs, key = carry
                key, akey, skey = jax.random.split(key, 3)
                action = self._action_fn(params, obs, akey)
                skeys = jax.random.split(skey, cfg.num_envs)
                env_states, next_obs, reward, done = jax.vmap(
                    env.step)(env_states, action, skeys)
                frame = {"obs": obs, "action": action,
                         "reward": reward, "next_obs": next_obs,
                         "done": done}
                return (env_states, next_obs, key), frame

            (env_states, obs, key), traj = jax.lax.scan(
                step, (env_states, obs, key), None,
                length=cfg.collect_steps)
            return env_states, obs, key, traj

        return collect

    def collect(self, weights) -> Dict[str, Any]:
        self.params = jax.tree_util.tree_map(
            lambda _, w: jnp.asarray(w), self.params, weights)
        self.env_states, self.obs, self.key, traj = self._collect(
            self.params, self.env_states, self.obs, self.key)
        rewards = np.asarray(traj["reward"])
        dones = np.asarray(traj["done"])
        track_episode_returns(self._ep_returns, self._done_returns,
                              rewards, dones)
        T, B = rewards.shape
        out = {k: np.asarray(v).reshape((T * B,) + v.shape[2:])
               for k, v in traj.items()}
        out["episode_returns"] = self._done_returns
        self._done_returns = []
        return out


class _DQNCollector(_CollectorBase):
    """Epsilon-greedy collection at a FIXED per-worker epsilon."""

    def _setup(self, cfg, worker_index, num_workers, pkey):
        self.q = QNetwork(self.env.observation_size,
                          self.env.action_size, hidden=cfg.hidden,
                          dueling=cfg.dueling,
                          num_atoms=cfg.num_atoms, v_min=cfg.v_min,
                          v_max=cfg.v_max)
        self.eps = collector_epsilon(worker_index, num_workers)
        self.params = self.q.init(pkey)

    def _action_fn(self, params, obs, key):
        akey, rkey = jax.random.split(key)
        greedy = jnp.argmax(self.q.apply(params, obs), axis=-1)
        random_a = jax.random.randint(rkey, greedy.shape, 0,
                                      self.env.action_size)
        explore = jax.random.uniform(akey, greedy.shape) < self.eps
        return jnp.where(explore, random_a, greedy)


class _ApexDriver:
    """The collector-fleet driver shared by ApexDQN and ApexDDPG: spawn
    actors, keep one collect in flight per actor, drain whatever is
    READY each iteration, ingest through the staged columnar path, run
    the learner's update block, re-arm drained actors with post-update
    weights."""

    _action_jnp_dtype = jnp.int32

    def _spawn_collectors(self, config, collector_cls) -> None:
        from .. import api
        from ..core.serialization import dumps_function
        blob = dumps_function(config)
        cls = api.remote(collector_cls)
        self._collectors = [
            cls.remote(blob, i, config.num_collectors)
            for i in range(config.num_collectors)]
        self._inflight: Dict[int, Any] = {}
        self._pending: Dict[str, np.ndarray] = {}

    def _collector_weights(self):
        """The (sub)tree of parameters collectors need — the full
        params by default; ApexDDPG ships the actor only."""
        return self.params

    def _arm(self, i: int, weights_ref: Any = None) -> None:
        from .. import api
        if weights_ref is None:
            weights_ref = api.put(jax.tree_util.tree_map(
                np.asarray, self._collector_weights()))
        self._inflight[i] = self._collectors[i].collect.remote(
            weights_ref)

    def _learner_update(self):
        """→ scalar loss for the metrics dict (learner-specific)."""
        raise NotImplementedError

    def _ingest_columnar(self, cols: Dict[str, np.ndarray]) -> int:
        """Concatenate into the pending staging columns; insert full
        ingest_chunk slices through the jitted add."""
        cfg = self.config
        for k in ("obs", "action", "reward", "next_obs", "done"):
            v = np.asarray(cols[k])
            self._pending[k] = v if k not in self._pending else \
                np.concatenate([self._pending[k], v])
        inserted = 0
        n = len(self._pending["obs"])
        while n - inserted >= cfg.ingest_chunk:
            sl = slice(inserted, inserted + cfg.ingest_chunk)
            batch = {
                "obs": jnp.asarray(self._pending["obs"][sl],
                                   jnp.float32),
                "action": jnp.asarray(self._pending["action"][sl],
                                      self._action_jnp_dtype),
                "reward": jnp.asarray(self._pending["reward"][sl],
                                      jnp.float32),
                "next_obs": jnp.asarray(self._pending["next_obs"][sl],
                                        jnp.float32),
                "done": jnp.asarray(self._pending["done"][sl],
                                    jnp.float32),
            }
            self.buffer = self._ingest_jit(self.buffer, batch)
            inserted += cfg.ingest_chunk
        self._pending = {k: v[inserted:]
                         for k, v in self._pending.items()}
        return inserted

    def training_step(self) -> Dict[str, Any]:
        from .. import api
        cfg = self.config
        t0 = time.perf_counter()
        for i in range(len(self._collectors)):
            if i not in self._inflight:
                self._arm(i)
        refs = {self._inflight[i]: i for i in self._inflight}
        # drain only what's READY: blocking on stragglers would degrade
        # the learner to the slowest collector (api.wait blocks until
        # at least one batch exists, so progress is guaranteed)
        ready, _ = api.wait(list(refs), num_returns=1, timeout=300.0)
        ready_set = set(ready)
        for r in list(refs):
            if r not in ready_set:
                more, _ = api.wait([r], num_returns=1, timeout=0.0)
                ready_set.update(more)
        received = 0
        drained = []
        for r in ready_set:
            i = refs[r]
            batch = api.get(self._inflight.pop(i), timeout=300.0)
            ep = batch.pop("episode_returns", None)
            if ep:
                self._ep_done_returns.extend(ep)
            received += len(batch["obs"])
            self._ingest_columnar(batch)
            drained.append(i)
        last_loss = self._learner_update()
        # re-arm AFTER the update with the post-update weights — one
        # shared put serves the whole drained set
        if drained:
            weights_ref = api.put(jax.tree_util.tree_map(
                np.asarray, self._collector_weights()))
            for i in drained:
                self._arm(i, weights_ref)
        dt = time.perf_counter() - t0
        return {
            "td_loss": float(last_loss),
            "buffer_size": int(self.buffer["size"]),
            "transitions_received": received,
            "env_steps_this_iter": received,
            "env_steps_per_s": received / dt,
            "episode_reward_mean": self.episode_reward_mean(),
        }

    def stop(self) -> None:
        from .. import api
        for c in self._collectors:
            try:
                api.kill(c)
            except Exception:
                pass
        self._collectors = []


@dataclasses.dataclass
class ApexDQNConfig(DQNConfig):
    num_collectors: int = 2
    collect_steps: int = 64        # env steps per env per collect call

    def build(self) -> "ApexDQN":
        return ApexDQN(self)


class ApexDQN(_ApexDriver, DQN):
    """The learner: external-input DQN machinery + a fleet of
    collector actors as the transition source."""

    _config_cls = ApexDQNConfig

    def __init__(self, config: ApexDQNConfig):
        if config.env is None:
            raise ValueError("ApexDQNConfig.env required")
        # the learner is EXACTLY the external-input DQN: device buffer,
        # compiled update scan, no inline env
        super().__init__(dataclasses.replace(config,
                                             external_input=True))
        self._spawn_collectors(config, _DQNCollector)


    def _learner_update(self):
        (self.params, self.target_params, self.opt_state, self.buffer,
         self.key, last_loss) = self._update_jit(
            self.params, self.target_params, self.opt_state,
            self.buffer, self.key,
            jnp.asarray(self._total_env_steps, jnp.float32))
        return last_loss


# ---------------------------------------------------------------------------
# Ape-X DDPG: the same distributed-replay architecture over the
# continuous-control learner (reference: rllib/algorithms/apex_ddpg/
# apex_ddpg.py — DDPG/TD3 learner fed by actors with a SPECTRUM of
# exploration-noise scales instead of epsilons).
# ---------------------------------------------------------------------------


def collector_noise_scale(i: int, n: int, base: float = 0.4,
                          alpha: float = 7.0) -> float:
    """Per-worker Gaussian exploration stddev on the Ape-X spectrum —
    the continuous analogue of `collector_epsilon`."""
    return collector_epsilon(i, n, base=base, alpha=alpha)


class _DDPGCollector(_CollectorBase):
    """Deterministic-policy collection with FIXED per-worker Gaussian
    action noise (the continuous Ape-X exploration spectrum)."""

    def _setup(self, cfg, worker_index, num_workers, pkey):
        from .policy import mlp_init
        self.sigma = collector_noise_scale(
            worker_index, num_workers) * self.env.action_high
        h = tuple(cfg.hidden)
        self.params = mlp_init(
            pkey, (self.env.observation_size,) + h
            + (self.env.action_size,))

    def _action_fn(self, params, obs, key):
        from .td3 import _relu_mlp
        high = self.env.action_high
        action = high * jnp.tanh(_relu_mlp(params, obs))
        return jnp.clip(
            action + self.sigma * jax.random.normal(key, action.shape),
            -high, high)


@dataclasses.dataclass
class ApexDDPGConfig(TD3Config):
    num_collectors: int = 2
    collect_steps: int = 64        # env steps per env per collect call
    ingest_chunk: int = 64         # fixed insert size (one compiled shape)

    def build(self) -> "ApexDDPG":
        return ApexDDPG(self)


class ApexDDPG(_ApexDriver, TD3):
    """The learner IS TD3/DDPG's update block over the device buffer;
    collectors ship noisy deterministic-policy transitions.  Collector
    weights are the ACTOR only (critics never leave the learner)."""

    _config_cls = ApexDDPGConfig
    _action_jnp_dtype = jnp.float32

    def __init__(self, config: ApexDDPGConfig):
        if config.env is None:
            raise ValueError("ApexDDPGConfig.env required")
        super().__init__(config)
        _, add_fn, _, _ = self._replay_ops
        self._ingest_jit = jax.jit(
            lambda buf, batch: add_fn(buf, batch, config.ingest_chunk))
        self._update_only = jax.jit(self._make_update_block())
        self._spawn_collectors(config, _DDPGCollector)

    def _collector_weights(self):
        return self.params["actor"]

    def training_step(self) -> Dict[str, Any]:
        # the driver loop re-arms with self.params["actor"] via _arm
        result = _ApexDriver.training_step(self)
        result["td_abs"] = result.pop("td_loss")
        return result

    def _learner_update(self):
        (self.params, self.targets, self.actor_opt_state,
         self.critic_opt_state, self.buffer, self.key,
         self._update_count, last_td) = self._update_only(
            self.params, self.targets, self.actor_opt_state,
            self.critic_opt_state, self.buffer, self.key,
            self._update_count)
        return last_td
