"""Vanilla policy gradient (REINFORCE).

Capability mirror of the reference's PG
(`rllib/algorithms/pg/pg.py` — the minimal on-policy algorithm: loss is
``-logp * discounted_return``, no critic, no clipping, one pass over each
batch).  TPU-first shape: rollout + return computation + the single
gradient step compile into ONE XLA program, sharing PPO's vectorized
rollout scan (`make_rollout_fn`) and connector plumbing.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .ppo import PPO, PPOConfig


@dataclasses.dataclass
class PGConfig(PPOConfig):
    lr: float = 4e-3
    entropy_coeff: float = 0.0
    normalize_advantages: bool = True

    def build(self) -> "PG":       # type: ignore[override]
        return PG(self)


def _returns_to_go(rewards, dones, gamma: float):
    """[T, B] rewards/dones → [T, B] discounted returns, zero-bootstrapped
    at episode ends AND at the rollout truncation (no critic exists to
    bootstrap with — the PG contract)."""

    def scan_fn(ret_next, frame):
        r, d = frame
        ret = r + gamma * ret_next * (1.0 - d)
        return ret, ret

    _, rets = jax.lax.scan(
        scan_fn, jnp.zeros_like(rewards[0]),
        (rewards, dones.astype(rewards.dtype)), reverse=True)
    return rets


class PG(PPO):
    _config_cls = PGConfig

    def _make_update_fn(self, batch_size: int):
        cfg, policy, optimizer = self.config, self.policy, self.optimizer

        def loss_fn(params, batch):
            logp, entropy, _value = jax.vmap(
                lambda o, a: policy.log_prob(params, o, a))(
                    batch["obs"], batch["action"])
            adv = batch["adv"]
            if cfg.normalize_advantages:
                adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            pg_loss = -(logp * adv).mean()
            ent = entropy.mean()
            return pg_loss - cfg.entropy_coeff * ent, \
                {"pg_loss": pg_loss, "entropy": ent}

        def update(params, opt_state, flat, key):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, flat)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, key, metrics

        return update

    def _make_train_iter(self):
        if self._recurrent:
            raise ValueError("PG does not support use_lstm; use PPO")
        if self.config.num_workers > 0:
            # PPO's worker path ships GAE advantages computed against the
            # value head — which PG's loss never trains, so those
            # advantages would come from a frozen random critic
            raise ValueError("PG does not support num_workers > 0: "
                             "rollout workers compute critic-based GAE "
                             "advantages and PG trains no critic; use "
                             "the inline path (num_workers=0) or PPO")
        cfg = self.config
        batch_size = cfg.num_envs * cfg.rollout_length
        update = self._make_update_fn(batch_size)

        def train_iter(params, opt_state, env_states, obs, conn_state,
                       key):
            (traj, env_states, obs, conn_state, _last_value,
             key) = self._rollout(params, env_states, obs, conn_state,
                                  key)
            ret = _returns_to_go(traj["reward"], traj["done"], cfg.gamma)
            flat = {
                "obs": traj["obs"].reshape(batch_size, -1),
                "action": traj["action"].reshape(
                    (batch_size,) if self.env.discrete
                    else (batch_size, -1)),
                "adv": ret.reshape(batch_size),
            }
            params, opt_state, key, metrics = update(
                params, opt_state, flat, key)
            metrics["reward_sum"] = traj["reward"].sum()
            return params, opt_state, env_states, obs, conn_state, key, \
                metrics, traj["reward"], traj["done"]

        return train_iter

    def get_state(self) -> Dict[str, Any]:
        state = super().get_state()
        state["algo"] = "PG"
        return state
