"""Policies: pure-JAX actor-critic networks.

The reference's `Policy` (`rllib/policy/policy.py:161`) has torch/tf
variants and a vestigial JAX template (`rllib/models/jax/fcnet.py`); here
the JAX MLP actor-critic is the native policy: params are pytrees, apply is
jit-friendly, discrete heads emit logits, continuous heads emit
(mean, log_std).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Params = Any


def mlp_init(key: jax.Array, sizes: Sequence[int]) -> List[Dict[str, Any]]:
    """He-initialized dense stack: [{w, b}] per layer (shared by
    MLPPolicy, DQN's QNetwork, and SAC's actor/critics)."""
    keys = jax.random.split(key, len(sizes) - 1)
    return [{"w": jax.random.normal(k, (a, b)) * math.sqrt(2.0 / a),
             "b": jnp.zeros((b,))}
            for k, a, b in zip(keys, sizes[:-1], sizes[1:])]


def mlp_apply(params: List[Dict[str, Any]], x: jnp.ndarray,
              activation=jnp.tanh) -> jnp.ndarray:
    """Apply an mlp_init stack; activation on all but the output layer."""
    for layer in params[:-1]:
        x = activation(x @ layer["w"] + layer["b"])
    return x @ params[-1]["w"] + params[-1]["b"]


class MLPPolicy:
    def __init__(self, obs_size: int, action_size: int, *,
                 discrete: bool = True,
                 hidden: Sequence[int] = (64, 64)):
        self.obs_size = obs_size
        self.action_size = action_size
        self.discrete = discrete
        self.hidden = tuple(hidden)

    # -- params -------------------------------------------------------------
    def init(self, key: jax.Array) -> Params:
        sizes = (self.obs_size,) + self.hidden
        n_out = self.action_size if self.discrete else 2 * self.action_size
        keys = jax.random.split(key, 3)
        return {
            "torso": mlp_init(keys[0], sizes),
            "pi": {"w": jax.random.normal(keys[-2],
                                          (sizes[-1], n_out)) * 0.01,
                   "b": jnp.zeros((n_out,))},
            "vf": {"w": jax.random.normal(keys[-1], (sizes[-1], 1)) * 1.0,
                   "b": jnp.zeros((1,))},
        }

    # -- forward ------------------------------------------------------------
    def _torso(self, params: Params, obs: jnp.ndarray) -> jnp.ndarray:
        x = obs
        for layer in params["torso"]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        return x

    def forward(self, params: Params, obs: jnp.ndarray
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """→ (policy head output, value)."""
        x = self._torso(params, obs)
        pi = x @ params["pi"]["w"] + params["pi"]["b"]
        v = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
        return pi, v

    # -- distributions ------------------------------------------------------
    def _sample_from(self, pi: jnp.ndarray, key: jax.Array):
        """Head output → (action, logp); shared by the feedforward and
        recurrent sampling paths."""
        if self.discrete:
            action = jax.random.categorical(key, pi)
            logp_all = jax.nn.log_softmax(pi)
            logp = jnp.take_along_axis(
                logp_all, action[..., None].astype(jnp.int32),
                axis=-1)[..., 0]
            return action, logp
        mean, log_std = jnp.split(pi, 2, axis=-1)
        log_std = jnp.clip(log_std, -5.0, 2.0)
        eps = jax.random.normal(key, mean.shape)
        action = mean + jnp.exp(log_std) * eps
        return action, self._gauss_logp(mean, log_std, action)

    def _logp_entropy_from(self, pi: jnp.ndarray, action: jnp.ndarray):
        """Head output + taken action → (logp, entropy)."""
        if self.discrete:
            logp_all = jax.nn.log_softmax(pi)
            logp = jnp.take_along_axis(
                logp_all, action[..., None].astype(jnp.int32),
                axis=-1)[..., 0]
            entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
            return logp, entropy
        mean, log_std = jnp.split(pi, 2, axis=-1)
        log_std = jnp.clip(log_std, -5.0, 2.0)
        logp = self._gauss_logp(mean, log_std, action)
        entropy = jnp.sum(log_std + 0.5 * math.log(2 * math.pi * math.e),
                          axis=-1)
        return logp, entropy

    def sample_action(self, params: Params, obs: jnp.ndarray,
                      key: jax.Array):
        """→ (action, logp, value)."""
        pi, v = self.forward(params, obs)
        action, logp = self._sample_from(pi, key)
        return action, logp, v

    def log_prob(self, params: Params, obs: jnp.ndarray,
                 action: jnp.ndarray):
        """→ (logp, entropy, value) for PPO updates."""
        pi, v = self.forward(params, obs)
        logp, entropy = self._logp_entropy_from(pi, action)
        return logp, entropy, v

    @staticmethod
    def _gauss_logp(mean, log_std, action):
        var = jnp.exp(2 * log_std)
        return jnp.sum(-((action - mean) ** 2) / (2 * var) - log_std
                       - 0.5 * math.log(2 * math.pi), axis=-1)

    def get_weights(self, params: Params):
        import numpy as np
        return jax.tree_util.tree_map(lambda x: np.asarray(x), params)

    def set_weights(self, params: Params, weights):
        return jax.tree_util.tree_map(lambda _, w: jnp.asarray(w),
                                      params, weights)


class LSTMPolicy(MLPPolicy):
    """Recurrent actor-critic: MLP torso → LSTM cell → pi/vf heads (the
    reference catalog's ``use_lstm`` wrapper, `rllib/models/catalog.py` +
    `models/torch/recurrent_net.py`, answered as an explicit-carry JAX
    cell that composes with `lax.scan`).

    The recurrent state is a ``(h, c)`` pair carried by the caller:
    rollouts thread it through their scan (resetting at episode
    boundaries), and PPO's sequence update replays the same scan under
    `grad` from the segment's initial state (`log_prob_seq`).
    """

    is_recurrent = True

    def __init__(self, obs_size: int, action_size: int, *,
                 discrete: bool = True, hidden: Sequence[int] = (64,),
                 lstm_size: int = 64):
        super().__init__(obs_size, action_size, discrete=discrete,
                         hidden=hidden)
        self.lstm_size = lstm_size

    def init(self, key: jax.Array) -> Params:
        sizes = (self.obs_size,) + self.hidden
        n_out = self.action_size if self.discrete else 2 * self.action_size
        kt, kl, kp, kv = jax.random.split(key, 4)
        in_dim = sizes[-1] + self.lstm_size
        return {
            "torso": mlp_init(kt, sizes),
            "lstm": {"w": jax.random.normal(
                kl, (in_dim, 4 * self.lstm_size)) * math.sqrt(1.0 / in_dim),
                "b": jnp.zeros((4 * self.lstm_size,))},
            "pi": {"w": jax.random.normal(
                kp, (self.lstm_size, n_out)) * 0.01,
                "b": jnp.zeros((n_out,))},
            "vf": {"w": jax.random.normal(kv, (self.lstm_size, 1)),
                   "b": jnp.zeros((1,))},
        }

    def initial_state(self, batch_size: Optional[int] = None):
        shape = ((self.lstm_size,) if batch_size is None
                 else (batch_size, self.lstm_size))
        return (jnp.zeros(shape), jnp.zeros(shape))

    def _cell(self, params: Params, x: jnp.ndarray, state):
        h, c = state
        z = jnp.concatenate([x, h], axis=-1) @ params["lstm"]["w"] \
            + params["lstm"]["b"]
        i, f, g, o = jnp.split(z, 4, axis=-1)
        c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return h, (h, c)

    def step_recurrent(self, params: Params, obs: jnp.ndarray, state):
        """One timestep: → (pi head, value, new_state).  Works on single
        obs [obs] or batches [B, obs] (state shaped to match)."""
        x = self._torso(params, obs)
        h, state = self._cell(params, x, state)
        pi = h @ params["pi"]["w"] + params["pi"]["b"]
        v = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
        return pi, v, state

    def sample_action_recurrent(self, params: Params, obs: jnp.ndarray,
                                state, key: jax.Array):
        """→ (action, logp, value, new_state)."""
        pi, v, state = self.step_recurrent(params, obs, state)
        action, logp = self._sample_from(pi, key)
        return action, logp, v, state

    def log_prob_seq(self, params: Params, obs_seq: jnp.ndarray,
                     action_seq: jnp.ndarray, done_seq: jnp.ndarray,
                     init_state):
        """Replay the rollout's recurrence under grad: [T, B, ...]
        sequences + the segment's initial state → (logp, entropy, value)
        each [T, B].  State resets AFTER a done step, mirroring the
        rollout's reset timing exactly."""
        def step(state, inp):
            obs, action, done = inp
            pi, v, state = self.step_recurrent(params, obs, state)
            logp, ent = self._logp_entropy_from(pi, action)
            keep = (1.0 - done.astype(jnp.float32))[..., None]
            state = jax.tree_util.tree_map(lambda s: s * keep, state)
            return state, (logp, ent, v)

        _, (logp, ent, v) = jax.lax.scan(
            step, init_state, (obs_seq, action_seq, done_seq))
        return logp, ent, v

    # forward() on a recurrent policy needs a state — fail loudly instead
    # of silently using the base class's (shape-incompatible) params
    def forward(self, params: Params, obs: jnp.ndarray):
        raise TypeError("LSTMPolicy.forward needs a recurrent state; use "
                        "step_recurrent(params, obs, state)")


class ConvPolicy(MLPPolicy):
    """Conv-torso actor-critic for image observations (the CNN half of
    the reference catalog's space-driven model selection,
    `rllib/models/catalog.py` get_model_v2 + `models/torch/visionnet`).

    Observations arrive FLAT (the rollout plumbing is shape-agnostic);
    the torso reshapes to ``obs_shape`` (H, W, C), runs a small conv
    stack (``conv_filters``: [(out_channels, kernel, stride), ...]),
    and feeds the flattened features through the inherited MLP heads.
    """

    def __init__(self, obs_shape, action_size: int, *,
                 discrete: bool = True,
                 conv_filters: Sequence[Tuple[int, int, int]] = (
                     (16, 3, 1), (32, 3, 1)),
                 hidden: Sequence[int] = (64,)):
        self.obs_shape = tuple(obs_shape)           # (H, W, C)
        self.conv_filters = tuple(conv_filters)
        h, w, c = self.obs_shape
        for (out_c, ksize, stride) in self.conv_filters:
            h = (h - ksize) // stride + 1
            w = (w - ksize) // stride + 1
            c = out_c
        self._feat_size = h * w * c
        # the inherited MLP torso/heads see the conv FEATURES, so size
        # the base policy by the feature map, not the raw pixels
        super().__init__(self._feat_size, action_size,
                         discrete=discrete, hidden=tuple(hidden))

    def init(self, key: jax.Array) -> Params:
        kc, km = jax.random.split(key)
        convs = []
        in_c = self.obs_shape[-1]
        for i, (out_c, ksize, _s) in enumerate(self.conv_filters):
            kk = jax.random.fold_in(kc, i)
            fan_in = ksize * ksize * in_c
            convs.append({
                "w": jax.random.normal(
                    kk, (ksize, ksize, in_c, out_c)) *
                math.sqrt(2.0 / fan_in),
                "b": jnp.zeros((out_c,))})
            in_c = out_c
        params = super().init(km)
        params["convs"] = convs
        return params

    def _torso(self, params: Params, obs: jnp.ndarray) -> jnp.ndarray:
        x = obs.reshape(self.obs_shape)[None]        # [1, H, W, C]
        for layer, (_o, _k, stride) in zip(params["convs"],
                                           self.conv_filters):
            x = jax.lax.conv_general_dilated(
                x, layer["w"], window_strides=(stride, stride),
                padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            x = jnp.tanh(x + layer["b"])
        return super()._torso(params, x.reshape(-1))
