"""Multi-agent RL: fixed-population envs + independent PPO learners.

Capability mirror of the reference's multi-agent stack
(/root/reference/rllib/env/multi_agent_env.py dict-keyed obs/actions;
per-policy training via the policy map in rllib/evaluation/) — redesigned
TPU-first: instead of dict-of-agents Python structures (dynamic shapes,
host control flow), the agent population is a STATIC LEADING AXIS.

  * `MultiAgentJaxEnv.step(state, actions[N], key)` returns
    obs[N, obs_size] / rewards[N] — every agent advances in one
    compiled program,
  * independent learning vmaps policy params over the agent axis: N
    policies initialize, act, and PPO-update as one XLA computation —
    "per-agent policies" become a batch dimension instead of a Python
    loop over policy objects.

Parameter sharing is the degenerate case (broadcast one param set).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .algorithm import Algorithm
from .policy import MLPPolicy


class MultiAgentJaxEnv:
    """Protocol: fixed ``n_agents``; states/obs/actions carry a leading
    agent axis (static shape → MXU-friendly, no per-agent host loop)."""

    n_agents: int
    observation_size: int
    action_size: int
    discrete: bool = True

    def reset(self, key):
        raise NotImplementedError

    def step(self, state, actions, key):
        """→ (state, obs[N, obs], rewards[N], done) — shared episode end.
        Envs AUTO-RESET on done (returning the new episode's state/obs),
        the same contract as the single-agent JaxEnv: collect scans carry
        env state across iterations and never reset explicitly."""
        raise NotImplementedError


class SpreadLine(MultiAgentJaxEnv):
    """N agents on a line must spread to their own targets while being
    pushed by their neighbors — a jittable mini "simple spread"
    (cooperative reward shaping per agent, conflict through collisions).
    """

    def __init__(self, n_agents: int = 4, horizon: int = 64):
        self.n_agents = n_agents
        self.horizon = horizon
        self.observation_size = 3   # (pos, own target, nearest-other dist)
        self.action_size = 3        # left / stay / right
        self.discrete = True

    def reset(self, key):
        pkey, _ = jax.random.split(key)
        pos = jax.random.uniform(pkey, (self.n_agents,), minval=-1.0,
                                 maxval=1.0)
        targets = jnp.linspace(-1.0, 1.0, self.n_agents)
        state = {"pos": pos, "targets": targets,
                 "t": jnp.zeros((), jnp.int32)}
        return state, self._obs(state)

    def _obs(self, state):
        pos, targets = state["pos"], state["targets"]
        diff = jnp.abs(pos[:, None] - pos[None, :]) \
            + jnp.eye(self.n_agents) * 1e9
        nearest = jnp.min(diff, axis=1)
        return jnp.stack([pos, targets, nearest], axis=1)

    def step(self, state, actions, key):
        delta = (actions.astype(jnp.float32) - 1.0) * 0.1
        pos = jnp.clip(state["pos"] + delta, -1.5, 1.5)
        # soft collision: agents within 0.1 push each other apart
        diff = pos[:, None] - pos[None, :]
        close = (jnp.abs(diff) < 0.1) & ~jnp.eye(self.n_agents, dtype=bool)
        push = jnp.sum(jnp.sign(diff) * close * 0.05, axis=1)
        pos = jnp.clip(pos + push, -1.5, 1.5)
        t = state["t"] + 1
        state = {"pos": pos, "targets": state["targets"], "t": t}
        dist = jnp.abs(pos - state["targets"])
        rewards = -dist - 0.25 * jnp.sum(close, axis=1)
        done = t >= self.horizon
        # auto-reset (the MultiAgentJaxEnv contract): past the horizon
        # the returned state/obs belong to a fresh episode — without
        # this, carried env states stay terminal forever and every
        # replayed transition after the first horizon is degenerate
        reset_state, _ = self.reset(key)
        state = jax.tree_util.tree_map(
            lambda r, c: jnp.where(done, r, c), reset_state, state)
        return state, self._obs(state), rewards, done


@dataclasses.dataclass
class IndependentPPOConfig:
    env: Optional[Callable[[], MultiAgentJaxEnv]] = None
    num_envs: int = 32
    rollout_length: int = 64
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    entropy_coeff: float = 0.01
    vf_coeff: float = 0.5
    lr: float = 3e-4
    num_sgd_epochs: int = 2
    hidden: tuple = (64, 64)
    share_parameters: bool = False
    seed: int = 0

    def build(self) -> "IndependentPPO":
        return IndependentPPO(self)


class IndependentPPO(Algorithm):
    """One PPO learner PER AGENT, all vmapped into a single program
    (reference: per-policy train ops over the policy_map — here the
    policy map is an array axis)."""

    _config_cls = IndependentPPOConfig

    def __init__(self, config: IndependentPPOConfig):
        super().__init__(config)
        cfg = config
        if cfg.env is None:
            raise ValueError("IndependentPPOConfig.env required")
        self.env = cfg.env()
        N = self.env.n_agents
        self.policy = MLPPolicy(self.env.observation_size,
                                self.env.action_size,
                                discrete=self.env.discrete,
                                hidden=cfg.hidden)
        key = jax.random.PRNGKey(cfg.seed)
        key, pkey, ekey = jax.random.split(key, 3)
        if cfg.share_parameters:
            shared = self.policy.init(pkey)
            self.params = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x, (N,) + x.shape), shared)
        else:
            self.params = jax.vmap(self.policy.init)(
                jax.random.split(pkey, N))
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = jax.vmap(self.optimizer.init)(self.params)
        ekeys = jax.random.split(ekey, cfg.num_envs)
        self.env_states, self.obs = jax.vmap(self.env.reset)(ekeys)
        self.key = key
        self._iter = jax.jit(self._make_train_iter())
        self._ep_rewards: list = []

    def _make_train_iter(self):
        cfg = self.config
        env = self.env
        policy = self.policy
        N = env.n_agents

        def rollout(params, env_states, obs, key):
            def tick(carry, _):
                env_states, obs, key = carry
                key, akey, skey = jax.random.split(key, 3)
                # vmap over envs (outer) x agents (inner, with per-agent
                # params) — one program moves every agent everywhere
                akeys = jax.random.split(akey, cfg.num_envs * N).reshape(
                    cfg.num_envs, N, 2)

                def agents_act(obs_e, keys_e):
                    return jax.vmap(policy.sample_action)(params, obs_e,
                                                          keys_e)

                actions, logps, values = jax.vmap(agents_act)(obs, akeys)
                skeys = jax.random.split(skey, cfg.num_envs)
                env_states, next_obs, rewards, done = jax.vmap(env.step)(
                    env_states, actions, skeys)
                frame = {"obs": obs, "action": actions, "logp": logps,
                         "value": values, "reward": rewards,
                         "done": jnp.broadcast_to(done[:, None],
                                                  (cfg.num_envs, N))}
                return (env_states, next_obs, key), frame

            (env_states, last_obs, key), traj = jax.lax.scan(
                tick, (env_states, obs, key), None,
                length=cfg.rollout_length)

            def agents_value(obs_e):
                _, v = jax.vmap(policy.forward)(params, obs_e)
                return v

            last_value = jax.vmap(agents_value)(last_obs)
            return traj, env_states, last_obs, last_value, key

        def gae(traj, last_value):
            def scan_fn(carry, frame):
                next_adv, next_value = carry
                nonterm = 1.0 - frame["done"].astype(jnp.float32)
                delta = frame["reward"] + cfg.gamma * next_value * nonterm \
                    - frame["value"]
                adv = delta + cfg.gamma * cfg.gae_lambda * nonterm * next_adv
                return (adv, frame["value"]), adv

            (_, _), adv = jax.lax.scan(
                scan_fn, (jnp.zeros_like(last_value), last_value), traj,
                reverse=True)
            return adv, adv + traj["value"]

        def per_agent_update(params_a, opt_state_a, batch_a, key_a):
            """One agent's PPO epochs over its own [T*B] batch."""
            n = batch_a["obs"].shape[0]

            def loss_fn(p, mb):
                logp, entropy, value = jax.vmap(
                    lambda o, a: policy.log_prob(p, o, a))(
                        mb["obs"], mb["action"])
                ratio = jnp.exp(logp - mb["logp"])
                adv = mb["adv"]
                adv = (adv - adv.mean()) / (adv.std() + 1e-8)
                pi_loss = -jnp.mean(jnp.minimum(
                    ratio * adv,
                    jnp.clip(ratio, 1 - cfg.clip_eps,
                             1 + cfg.clip_eps) * adv))
                vf_loss = 0.5 * jnp.mean((value - mb["ret"]) ** 2)
                ent = jnp.mean(entropy)
                return pi_loss + cfg.vf_coeff * vf_loss \
                    - cfg.entropy_coeff * ent

            def epoch(carry, _):
                p, os_, key = carry
                key, pkey = jax.random.split(key)
                idx = jax.random.permutation(pkey, n)
                mb = jax.tree_util.tree_map(lambda x: x[idx], batch_a)
                loss, grads = jax.value_and_grad(loss_fn)(p, mb)
                updates, os_ = self.optimizer.update(grads, os_, p)
                p = optax.apply_updates(p, updates)
                return (p, os_, key), loss

            (params_a, opt_state_a, _), losses = jax.lax.scan(
                epoch, (params_a, opt_state_a, key_a), None,
                length=cfg.num_sgd_epochs)
            return params_a, opt_state_a, losses[-1]

        def train_iter(params, opt_state, env_states, obs, key):
            traj, env_states, obs, last_value, key = rollout(
                params, env_states, obs, key)
            adv, ret = gae(traj, last_value)
            TB = cfg.rollout_length * cfg.num_envs
            # [T, B, N, ...] -> per-agent [N, T*B, ...]
            def to_agent_major(x):
                x = jnp.moveaxis(x, 2, 0)
                return x.reshape((N, TB) + x.shape[3:])

            batch = {
                "obs": to_agent_major(traj["obs"]),
                "action": to_agent_major(traj["action"]),
                "logp": to_agent_major(traj["logp"]),
                "adv": to_agent_major(adv),
                "ret": to_agent_major(ret),
            }
            key, ukey = jax.random.split(key)
            params, opt_state, losses = jax.vmap(per_agent_update)(
                params, opt_state, batch, jax.random.split(ukey, N))
            mean_reward = traj["reward"].mean(axis=(0, 1))  # per agent
            return (params, opt_state, env_states, obs, key,
                    losses, mean_reward)

        return train_iter

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        (self.params, self.opt_state, self.env_states, self.obs, self.key,
         losses, mean_reward) = self._iter(
            self.params, self.opt_state, self.env_states, self.obs,
            self.key)
        mean_reward = np.asarray(mean_reward)
        self._ep_rewards.append(float(mean_reward.mean()))
        return {
            "loss_per_agent": np.asarray(losses).tolist(),
            "reward_mean_per_agent": mean_reward.tolist(),
            "reward_mean": float(mean_reward.mean()),
            "env_steps_this_iter":
                cfg.num_envs * cfg.rollout_length * self.env.n_agents,
        }

    def get_state(self) -> Dict[str, Any]:
        return {"params": jax.tree_util.tree_map(np.asarray, self.params),
                "iteration": self.iteration}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = jax.tree_util.tree_map(jnp.asarray, state["params"])
        self.iteration = state.get("iteration", 0)
