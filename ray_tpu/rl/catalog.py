"""Model catalog: build policy networks from env spaces + model config.

Capability mirror of the reference's `rllib/models/catalog.py:1`
(ModelCatalog.get_model_v2 — space-driven model construction plus a
custom-model registry).  The native policy family is pure-JAX
(`policy.py` MLPPolicy); the catalog maps an env's observation/action
space and a ``model`` config dict onto it, applies connector-driven
observation resizing, and lets users register custom policy classes by
name — the `register_custom_model` flow."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from .env import JaxEnv
from .policy import ConvPolicy, LSTMPolicy, MLPPolicy

_CUSTOM_MODELS: Dict[str, Callable[..., Any]] = {}

DEFAULT_MODEL: Dict[str, Any] = {
    "hidden": (64, 64),
    "conv_filters": None,     # None -> catalog default for image spaces
    "use_lstm": False,        # recurrent wrapper (reference: catalog
    "lstm_cell_size": 64,     # use_lstm / lstm_cell_size model options)
    "custom_model": None,
    "custom_model_config": {},
}


def register_custom_model(name: str, factory: Callable[..., Any]) -> None:
    """factory(obs_size, action_size, discrete=..., **custom_config) ->
    policy object with the MLPPolicy interface (init/forward/
    sample_action/log_prob)."""
    _CUSTOM_MODELS[name] = factory


def build_policy(env: JaxEnv, model: Optional[Dict[str, Any]] = None,
                 obs_size_override: Optional[int] = None):
    """Policy for an env's spaces (reference: get_model_v2).

    ``obs_size_override``: observation size AFTER the agent connector
    pipeline (e.g. FrameStack multiplies it) — pass
    ``pipeline.out_size(env.observation_size)``."""
    cfg = dict(DEFAULT_MODEL)
    cfg.update(model or {})
    obs_size = obs_size_override or env.observation_size
    custom = cfg.get("custom_model")
    if custom and cfg.get("use_lstm"):
        raise ValueError(
            "custom_model + use_lstm is not supported: recurrence must "
            "live inside the custom policy (give it is_recurrent=True "
            "and the LSTMPolicy interface)")
    if custom:
        if custom not in _CUSTOM_MODELS:
            raise ValueError(
                f"custom model {custom!r} not registered "
                f"(known: {sorted(_CUSTOM_MODELS)})")
        return _CUSTOM_MODELS[custom](
            obs_size, env.action_size, discrete=env.discrete,
            **cfg.get("custom_model_config", {}))
    # image observation space -> conv torso (the reference catalog's
    # vision-net selection); connectors that resize flat obs keep the
    # MLP path since the image geometry no longer applies
    obs_shape = getattr(env, "observation_shape", None)
    is_image = obs_shape is not None and len(obs_shape) == 3 and \
        obs_size == env.observation_size
    if cfg.get("use_lstm"):
        if is_image:
            raise ValueError(
                "use_lstm on an image-observation env would silently "
                "drop the conv torso (the LSTMPolicy is MLP-bodied); "
                "flatten the observations with a connector, or register "
                "a custom Conv+LSTM policy (is_recurrent=True with the "
                "LSTMPolicy interface)")
        return LSTMPolicy(obs_size, env.action_size,
                          discrete=env.discrete,
                          hidden=tuple(cfg["hidden"]),
                          lstm_size=cfg.get("lstm_cell_size", 64))
    if is_image:
        return ConvPolicy(obs_shape, env.action_size,
                          discrete=env.discrete,
                          conv_filters=cfg.get("conv_filters")
                          or ((16, 3, 1), (32, 3, 1)),
                          hidden=tuple(cfg["hidden"]))
    return MLPPolicy(obs_size, env.action_size, discrete=env.discrete,
                     hidden=tuple(cfg["hidden"]))
