"""Offline RL: datasets of experience, behavioral cloning, OPE.

Capability mirror of the reference's offline stack
(/root/reference/rllib/offline/ — JsonWriter/JsonReader dataset IO,
`rllib/offline/estimators/importance_sampling.py` off-policy estimation,
BC/MARWIL in rllib/algorithms/bc) — TPU-first: datasets are columnar
array batches (one device transfer, MXU-shaped minibatches), the BC
update is one jitted scan over minibatches, and collection reuses the
compiled rollout program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .algorithm import Algorithm
from .env import JaxEnv
from .policy import MLPPolicy


# ------------------------------------------------------------------ datasets
def collect_dataset(env_factory: Callable[[], JaxEnv], policy_fn,
                    *, n_steps: int, num_envs: int = 32,
                    seed: int = 0) -> Dict[str, np.ndarray]:
    """Roll a (possibly scripted) policy and record columnar experience.

    ``policy_fn(obs, key) -> action`` is any jittable function — a trained
    policy's sampler or a scripted expert.  Returns ENV-MAJOR flattened
    columns: obs, action, reward, done, next_obs (the reference's
    SampleBatch columns, rllib/policy/sample_batch.py).  Env-major order
    means each env's trajectory is a contiguous run of rows with episode
    boundaries marked by ``done`` — so sequence consumers (DT's
    episodes_from_columns) can reconstruct real episodes; minibatch
    consumers (BC/CQL/CRR/MARWIL) permute rows anyway and are
    order-indifferent.
    """
    env = env_factory()
    key = jax.random.PRNGKey(seed)
    key, ekey = jax.random.split(key)
    ekeys = jax.random.split(ekey, num_envs)
    states, obs = jax.vmap(env.reset)(ekeys)
    steps = -(-n_steps // num_envs)

    def tick(carry, _):
        states, obs, key = carry
        key, akey, skey = jax.random.split(key, 3)
        actions = jax.vmap(policy_fn)(obs, jax.random.split(akey, num_envs))
        states, next_obs, rewards, dones = jax.vmap(env.step)(
            states, actions, jax.random.split(skey, num_envs))
        frame = {"obs": obs, "action": actions, "reward": rewards,
                 "done": dones, "next_obs": next_obs}
        return (states, next_obs, key), frame

    (_, _, _), traj = jax.lax.scan(tick, (states, obs, key), None,
                                   length=steps)
    flat = {}
    for k, v in traj.items():
        v = np.asarray(v)                       # [T, B, ...]
        v = np.swapaxes(v, 0, 1)                # env-major [B, T, ...]
        flat[k] = v.reshape((-1,) + v.shape[2:])[:n_steps]
    # env_id marks the block junctions: each env's TRAILING partial
    # episode has done=0, so without it episode reconstruction would
    # splice env i's tail onto env i+1's first episode
    flat["env_id"] = np.repeat(np.arange(num_envs), steps)[:n_steps]
    return flat


def save_dataset(path: str, batch: Dict[str, np.ndarray]) -> None:
    np.savez_compressed(path, **batch)


def load_dataset(path: str) -> Dict[str, np.ndarray]:
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


# ------------------------------------------------------ behavioral cloning
@dataclasses.dataclass
class BCConfig:
    env: Optional[Callable[[], JaxEnv]] = None
    dataset: Optional[Dict[str, np.ndarray]] = None
    lr: float = 1e-3
    batch_size: int = 256
    epochs_per_iter: int = 1
    hidden: tuple = (64, 64)
    seed: int = 0

    def build(self) -> "BC":
        return BC(self)


class BC(Algorithm):
    """Behavioral cloning: maximize log pi(a|s) over the dataset
    (reference: rllib/algorithms/bc — MARWIL with beta=0)."""

    _config_cls = BCConfig

    def __init__(self, config: BCConfig):
        super().__init__(config)
        if config.env is None or config.dataset is None:
            raise ValueError("BCConfig.env and BCConfig.dataset required")
        if config.epochs_per_iter < 1:
            raise ValueError("epochs_per_iter must be >= 1 (a zero-epoch "
                             "iteration would report no loss)")
        self.env = config.env()
        self.policy = MLPPolicy(self.env.observation_size,
                                self.env.action_size,
                                discrete=self.env.discrete,
                                hidden=config.hidden)
        self.key = jax.random.PRNGKey(config.seed)
        self.key, pkey = jax.random.split(self.key)
        self.params = self.policy.init(pkey)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        ds = config.dataset
        n = (len(ds["obs"]) // config.batch_size) * config.batch_size
        self._obs = jnp.asarray(ds["obs"][:n])
        self._act = jnp.asarray(ds["action"][:n])
        self._epoch = jax.jit(self._make_epoch_fn(n))

    def _make_epoch_fn(self, n: int):
        cfg = self.config
        policy = self.policy
        n_mb = n // cfg.batch_size

        def epoch(params, opt_state, key):
            key, pkey = jax.random.split(key)
            idx = jax.random.permutation(pkey, n).reshape(
                n_mb, cfg.batch_size)

            def mb_step(carry, ix):
                params, opt_state = carry

                def loss_fn(p):
                    logp, _, _ = jax.vmap(
                        lambda o, a: policy.log_prob(p, o, a))(
                            self._obs[ix], self._act[ix])
                    return -jnp.mean(logp)

                loss, grads = jax.value_and_grad(loss_fn)(params)
                updates, opt_state = self.optimizer.update(
                    grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            (params, opt_state), losses = jax.lax.scan(
                mb_step, (params, opt_state), idx)
            return params, opt_state, key, losses.mean()

        return epoch

    def training_step(self) -> Dict[str, Any]:
        loss = None
        for _ in range(self.config.epochs_per_iter):
            self.params, self.opt_state, self.key, loss = self._epoch(
                self.params, self.opt_state, self.key)
        return {"bc_loss": float(loss),
                "env_steps_this_iter": 0}

    def action_fn(self):
        """Greedy jittable policy for deployment/eval."""
        policy = self.policy
        params = self.params

        def act(obs, key):
            return policy.greedy_action(params, obs) \
                if hasattr(policy, "greedy_action") \
                else policy.sample_action(params, obs, key)[0]
        return act

    def get_state(self) -> Dict[str, Any]:
        return {"params": self.policy.get_weights(self.params),
                "iteration": self.iteration}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = self.policy.set_weights(self.params, state["params"])
        self.iteration = state.get("iteration", 0)


# ------------------------------------------------------------- MARWIL
@dataclasses.dataclass
class MARWILConfig:
    env: Optional[Callable[[], JaxEnv]] = None
    dataset: Optional[Dict[str, np.ndarray]] = None
    beta: float = 1.0              # advantage-weighting temperature;
    #   beta=0 degenerates to BC (the reference's exact relationship)
    gamma: float = 0.99
    vf_coeff: float = 1.0
    weight_clip: float = 20.0      # cap exp(beta * A / c) (reference's
    #   moving-average normalization guards the same blowup)
    lr: float = 1e-3
    batch_size: int = 256
    epochs_per_iter: int = 1
    hidden: tuple = (64, 64)
    seed: int = 0

    def build(self) -> "MARWIL":
        return MARWIL(self)


class MARWIL(Algorithm):
    """Monotonic Advantage Re-Weighted Imitation Learning (reference:
    rllib/algorithms/marwil/marwil.py:1 — exponentially
    advantage-weighted behavioral cloning with a jointly learned value
    function).  Advantages are one-step TD residuals against the
    learned V (the columnar dataset carries next_obs/done, so no
    episode reconstruction is needed), normalized by a running
    root-mean-square like the reference's moving-average c².  One
    jitted epoch over permuted minibatches, like BC/CQL.
    """

    _config_cls = MARWILConfig

    def __init__(self, config: MARWILConfig):
        super().__init__(config)
        if config.env is None or config.dataset is None:
            raise ValueError("MARWILConfig.env and MARWILConfig.dataset "
                             "required")
        if config.epochs_per_iter < 1:
            raise ValueError("epochs_per_iter must be >= 1 (a zero-epoch "
                             "iteration would report no loss)")
        self.env = config.env()
        self.policy = MLPPolicy(self.env.observation_size,
                                self.env.action_size,
                                discrete=self.env.discrete,
                                hidden=config.hidden)
        from .policy import mlp_apply, mlp_init
        self._v_apply = mlp_apply
        self.key = jax.random.PRNGKey(config.seed)
        self.key, pkey, vkey = jax.random.split(self.key, 3)
        self.params = {
            "pi": self.policy.init(pkey),
            "v": mlp_init(vkey, (self.env.observation_size,)
                          + tuple(config.hidden) + (1,)),
        }
        self.adv_rms = jnp.ones(())     # running sqrt(E[A^2])
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        ds = config.dataset
        n = (len(ds["obs"]) // config.batch_size) * config.batch_size
        if n == 0:
            raise ValueError(
                f"dataset has {len(ds['obs'])} rows < batch_size="
                f"{config.batch_size}: an epoch would run zero "
                f"minibatches and train nothing")
        self._data = {
            "obs": jnp.asarray(ds["obs"][:n], jnp.float32),
            "action": jnp.asarray(ds["action"][:n]),
            "reward": jnp.asarray(ds["reward"][:n], jnp.float32),
            "next_obs": jnp.asarray(ds["next_obs"][:n], jnp.float32),
            "done": jnp.asarray(ds["done"][:n], jnp.float32),
        }
        self._epoch = jax.jit(self._make_epoch_fn(n))

    def _make_epoch_fn(self, n: int):
        cfg = self.config
        policy, v_apply, data = self.policy, self._v_apply, self._data
        n_mb = n // cfg.batch_size

        def epoch(params, opt_state, adv_rms, key):
            key, pkey = jax.random.split(key)
            idx = jax.random.permutation(pkey, n).reshape(
                n_mb, cfg.batch_size)

            def mb_step(carry, ix):
                params, opt_state, adv_rms = carry
                batch = jax.tree_util.tree_map(lambda c: c[ix], data)

                def loss_fn(p):
                    v = v_apply(p["v"], batch["obs"])[..., 0]
                    v_next = v_apply(p["v"], batch["next_obs"])[..., 0]
                    target = batch["reward"] + cfg.gamma \
                        * (1.0 - batch["done"]) \
                        * jax.lax.stop_gradient(v_next)
                    vf_loss = jnp.mean((v - target) ** 2)
                    adv = jax.lax.stop_gradient(target - v)
                    weights = jnp.minimum(
                        jnp.exp(cfg.beta * adv
                                / jnp.maximum(adv_rms, 1e-6)),
                        cfg.weight_clip)
                    logp, _, _ = jax.vmap(
                        lambda o, a: policy.log_prob(p["pi"], o, a))(
                            batch["obs"], batch["action"])
                    pi_loss = -jnp.mean(weights * logp)
                    return pi_loss + cfg.vf_coeff * vf_loss, \
                        (pi_loss, vf_loss, adv)

                (loss, (pi_loss, vf_loss, adv)), grads = \
                    jax.value_and_grad(loss_fn, has_aux=True)(params)
                # running RMS of advantages (the reference's moving
                # average c^2: c^2 += 1e-8 * (mean(A^2) - c^2))
                adv_rms = jnp.sqrt(
                    0.99 * adv_rms ** 2 + 0.01 * jnp.mean(adv ** 2))
                updates, opt_state = self.optimizer.update(
                    grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state, adv_rms), (pi_loss, vf_loss)

            (params, opt_state, adv_rms), (pi_losses, vf_losses) = \
                jax.lax.scan(mb_step, (params, opt_state, adv_rms), idx)
            return (params, opt_state, adv_rms, key,
                    pi_losses.mean(), vf_losses.mean())

        return epoch

    def training_step(self) -> Dict[str, Any]:
        pi_loss = vf_loss = None
        for _ in range(self.config.epochs_per_iter):
            (self.params, self.opt_state, self.adv_rms, self.key,
             pi_loss, vf_loss) = self._epoch(
                self.params, self.opt_state, self.adv_rms, self.key)
        return {"policy_loss": float(pi_loss),
                "vf_loss": float(vf_loss),
                "adv_rms": float(self.adv_rms),
                "env_steps_this_iter": 0}

    def action_fn(self):
        """Greedy jittable policy for deployment/eval."""
        policy = self.policy
        params = self.params["pi"]

        def act(obs, key):
            return policy.greedy_action(params, obs) \
                if hasattr(policy, "greedy_action") \
                else policy.sample_action(params, obs, key)[0]
        return act

    def get_state(self) -> Dict[str, Any]:
        to_np = lambda t: jax.tree_util.tree_map(np.asarray, t)  # noqa: E731
        return {"params": to_np(self.params),
                "adv_rms": float(self.adv_rms),
                "iteration": self.iteration}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = jax.tree_util.tree_map(
            lambda _, x: jnp.asarray(x), self.params, state["params"])
        self.adv_rms = jnp.asarray(state.get("adv_rms", 1.0))
        self.iteration = state.get("iteration", 0)


# ------------------------------------------------- off-policy estimation
# ------------------------------------------------------ conservative Q
@dataclasses.dataclass
class CQLConfig:
    env: Optional[Callable[[], JaxEnv]] = None
    dataset: Optional[Dict[str, np.ndarray]] = None
    lr: float = 1e-3
    batch_size: int = 256
    epochs_per_iter: int = 1
    gamma: float = 0.99
    tau: float = 0.01              # Polyak target-average rate
    cql_alpha: float = 1.0         # conservative-penalty weight
    double_q: bool = True
    hidden: tuple = (64, 64)
    seed: int = 0

    def build(self) -> "CQL":
        return CQL(self)


class CQL(Algorithm):
    """Conservative Q-Learning, discrete actions (reference:
    `rllib/algorithms/cql/cql.py` — the flagship offline algorithm).

    Standard (double-)DQN TD learning on the fixed dataset plus the CQL
    regularizer ``alpha * E[logsumexp_a Q(s, a) - Q(s, a_data)]``, which
    pushes down Q-values of actions the dataset never took — the
    out-of-distribution overestimation that sinks naive offline DQN.
    One jitted epoch function over permuted minibatches, like BC.
    """

    _config_cls = CQLConfig

    def __init__(self, config: CQLConfig):
        super().__init__(config)
        if config.env is None or config.dataset is None:
            raise ValueError("CQLConfig.env and CQLConfig.dataset required")
        if config.epochs_per_iter < 1:
            raise ValueError("epochs_per_iter must be >= 1 (a zero-epoch "
                             "iteration would report no loss)")
        self.env = config.env()
        if not self.env.discrete:
            raise ValueError("this CQL implementation is discrete-action "
                             "(the reference's continuous variant adds "
                             "an SAC actor)")
        from .dqn import QNetwork
        self.q = QNetwork(self.env.observation_size, self.env.action_size,
                          hidden=config.hidden)
        self.key = jax.random.PRNGKey(config.seed)
        self.key, pkey = jax.random.split(self.key)
        self.params = self.q.init(pkey)
        self.target_params = jax.tree_util.tree_map(lambda x: x,
                                                    self.params)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        ds = config.dataset
        n = (len(ds["obs"]) // config.batch_size) * config.batch_size
        self._data = {
            "obs": jnp.asarray(ds["obs"][:n], jnp.float32),
            "action": jnp.asarray(ds["action"][:n], jnp.int32),
            "reward": jnp.asarray(ds["reward"][:n], jnp.float32),
            "next_obs": jnp.asarray(ds["next_obs"][:n], jnp.float32),
            "done": jnp.asarray(ds["done"][:n], jnp.float32),
        }
        self._epoch = jax.jit(self._make_epoch_fn(n))

    def _make_epoch_fn(self, n: int):
        cfg = self.config
        q = self.q
        n_mb = n // cfg.batch_size

        def epoch(params, target_params, opt_state, key):
            key, pkey = jax.random.split(key)
            idx = jax.random.permutation(pkey, n).reshape(
                n_mb, cfg.batch_size)

            def mb_step(carry, ix):
                params, target_params, opt_state = carry
                batch = jax.tree_util.tree_map(lambda x: x[ix],
                                               self._data)

                def loss_fn(p):
                    from .dqn import dqn_target
                    qvals = q.apply(p, batch["obs"])           # [B, A]
                    q_sa = jnp.take_along_axis(
                        qvals, batch["action"][:, None], axis=-1)[:, 0]
                    target = dqn_target(q.apply, p, target_params,
                                        batch["reward"],
                                        batch["next_obs"], batch["done"],
                                        cfg.gamma, cfg.double_q)
                    td = q_sa - target
                    # the conservative term: minimize OOD action values
                    cql = jnp.mean(jax.nn.logsumexp(qvals, axis=-1)
                                   - q_sa)
                    return jnp.mean(td ** 2) + cfg.cql_alpha * cql, cql

                (loss, cql), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                updates, opt_state = self.optimizer.update(
                    grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                target_params = jax.tree_util.tree_map(
                    lambda t, p_: (1 - cfg.tau) * t + cfg.tau * p_,
                    target_params, params)
                return (params, target_params, opt_state), (loss, cql)

            (params, target_params, opt_state), (losses, cqls) = \
                jax.lax.scan(mb_step, (params, target_params, opt_state),
                             idx)
            return (params, target_params, opt_state, key,
                    losses.mean(), cqls.mean())

        return epoch

    def training_step(self) -> Dict[str, Any]:
        loss = cql = None
        for _ in range(self.config.epochs_per_iter):
            (self.params, self.target_params, self.opt_state, self.key,
             loss, cql) = self._epoch(self.params, self.target_params,
                                      self.opt_state, self.key)
        return {"cql_loss": float(loss), "cql_gap": float(cql),
                "env_steps_this_iter": 0}

    def action_fn(self):
        """Greedy jittable policy for deployment/eval."""
        q, params = self.q, self.params

        def act(obs, key):
            return jnp.argmax(q.apply(params, obs), axis=-1)
        return act

    def get_state(self) -> Dict[str, Any]:
        to_np = jax.tree_util.tree_map
        return {"params": to_np(np.asarray, self.params),
                "target_params": to_np(np.asarray, self.target_params),
                "iteration": self.iteration}

    def set_state(self, state: Dict[str, Any]) -> None:
        as_dev = lambda t, w: jax.tree_util.tree_map(  # noqa: E731
            lambda _, x: jnp.asarray(x), t, w)
        self.params = as_dev(self.params, state["params"])
        self.target_params = as_dev(self.target_params,
                                    state["target_params"])
        self.iteration = state.get("iteration", 0)


def importance_sampling_estimate(policy: MLPPolicy, params,
                                 episodes: Dict[str, np.ndarray],
                                 behavior_logp: np.ndarray,
                                 gamma: float = 0.99,
                                 weighted: bool = True) -> Dict[str, float]:
    """Per-episode (W)IS estimate of the target policy's value from
    behavior data (reference: rllib/offline/estimators/
    importance_sampling.py / weighted_importance_sampling.py).

    ``episodes`` columns obs/action/reward/done delimit episodes by
    ``done``; ``behavior_logp`` are the behavior policy's log-probs for
    the logged actions.
    """
    logp, _, _ = jax.vmap(lambda o, a: policy.log_prob(params, o, a))(
        jnp.asarray(episodes["obs"]), jnp.asarray(episodes["action"]))
    ratios = np.exp(np.asarray(logp) - behavior_logp)
    rewards, dones = episodes["reward"], episodes["done"]
    ep_returns, ep_weights = [], []
    w, ret, disc = 1.0, 0.0, 1.0
    for t in range(len(rewards)):
        w *= float(ratios[t])
        ret += disc * float(rewards[t])
        disc *= gamma
        if dones[t]:
            ep_returns.append(ret)
            ep_weights.append(w)
            w, ret, disc = 1.0, 0.0, 1.0
    if not ep_returns:
        ep_returns, ep_weights = [ret], [w]
    ep_returns = np.asarray(ep_returns)
    ep_weights = np.asarray(ep_weights)
    if weighted:
        denom = max(ep_weights.sum(), 1e-8)
        v = float((ep_weights * ep_returns).sum() / denom)
    else:
        v = float((ep_weights * ep_returns).mean())
    return {"v_target": v,
            "v_behavior": float(ep_returns.mean()),
            "num_episodes": int(len(ep_returns)),
            "mean_ratio": float(ratios.mean())}


# ------------------------------------------------- critic-regularized
@dataclasses.dataclass
class CRRConfig:
    env: Optional[Callable[[], JaxEnv]] = None
    dataset: Optional[Dict[str, np.ndarray]] = None
    weight_fn: str = "binary"      # "binary" (1[A>0]) | "exp"
    beta: float = 1.0              # exp-weight temperature
    weight_clip: float = 20.0      # cap on exp weights
    gamma: float = 0.99
    tau: float = 0.01              # Polyak target-average rate
    lr: float = 1e-3
    batch_size: int = 256
    epochs_per_iter: int = 1
    hidden: tuple = (64, 64)
    seed: int = 0

    def build(self) -> "CRR":
        return CRR(self)


class CRR(Algorithm):
    """Critic-Regularized Regression, discrete actions (reference:
    `rllib/algorithms/crr/crr.py` — offline actor-critic where the actor
    is advantage-filtered behavioral cloning).

    The critic is a Q-network TD-trained against the CURRENT policy's
    expected target value (``y = r + g*(1-d)*E_{a'~pi} Q_tgt(s',a')`` —
    exact for discrete actions, no sampling needed); the actor clones
    only transitions the critic approves: weight ``1[A(s,a) > 0]``
    ("binary") or ``exp(A/beta)`` ("exp"), with
    ``A(s,a) = Q(s,a) - E_{a~pi} Q(s,a)``.  Against CQL's pessimism,
    CRR's filter needs no OOD penalty — the actor simply never imitates
    dataset actions its critic dislikes.  One jitted epoch over
    permuted minibatches, like BC/MARWIL/CQL.
    """

    _config_cls = CRRConfig

    def __init__(self, config: CRRConfig):
        super().__init__(config)
        if config.env is None or config.dataset is None:
            raise ValueError("CRRConfig.env and CRRConfig.dataset required")
        if config.epochs_per_iter < 1:
            raise ValueError("epochs_per_iter must be >= 1 (a zero-epoch "
                             "iteration would report no loss)")
        if config.weight_fn not in ("binary", "exp"):
            raise ValueError(f"weight_fn={config.weight_fn!r} not in "
                             "('binary', 'exp')")
        self.env = config.env()
        if not self.env.discrete:
            raise ValueError("this CRR implementation is discrete-action "
                             "(the reference's continuous variant samples "
                             "the policy for the advantage expectation)")
        from .dqn import QNetwork
        self.policy = MLPPolicy(self.env.observation_size,
                                self.env.action_size, discrete=True,
                                hidden=config.hidden)
        self.q = QNetwork(self.env.observation_size, self.env.action_size,
                          hidden=config.hidden)
        self.key = jax.random.PRNGKey(config.seed)
        self.key, pkey, qkey = jax.random.split(self.key, 3)
        self.params = {"pi": self.policy.init(pkey),
                       "q": self.q.init(qkey)}
        self.target_q = jax.tree_util.tree_map(lambda x: x,
                                               self.params["q"])
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        ds = config.dataset
        n = (len(ds["obs"]) // config.batch_size) * config.batch_size
        if n == 0:
            raise ValueError(
                f"dataset has {len(ds['obs'])} rows < batch_size="
                f"{config.batch_size}: an epoch would run zero "
                f"minibatches and train nothing")
        self._data = {
            "obs": jnp.asarray(ds["obs"][:n], jnp.float32),
            "action": jnp.asarray(ds["action"][:n], jnp.int32),
            "reward": jnp.asarray(ds["reward"][:n], jnp.float32),
            "next_obs": jnp.asarray(ds["next_obs"][:n], jnp.float32),
            "done": jnp.asarray(ds["done"][:n], jnp.float32),
        }
        self._epoch = jax.jit(self._make_epoch_fn(n))

    def _make_epoch_fn(self, n: int):
        cfg = self.config
        policy, q = self.policy, self.q
        n_mb = n // cfg.batch_size

        def epoch(params, target_q, opt_state, key):
            key, pkey = jax.random.split(key)
            idx = jax.random.permutation(pkey, n).reshape(
                n_mb, cfg.batch_size)

            def mb_step(carry, ix):
                params, target_q, opt_state = carry
                batch = jax.tree_util.tree_map(lambda x: x[ix],
                                               self._data)

                def loss_fn(p):
                    B = batch["obs"].shape[0]
                    qvals = q.apply(p["q"], batch["obs"])       # [B, A]
                    q_sa = qvals[jnp.arange(B), batch["action"]]
                    # policy distribution at s' for the expected target
                    pi_next, _ = jax.vmap(
                        lambda o: policy.forward(p["pi"], o))(
                            batch["next_obs"])
                    pi_next = jax.nn.softmax(
                        jax.lax.stop_gradient(pi_next))
                    q_next = q.apply(target_q, batch["next_obs"])
                    v_next = (pi_next * q_next).sum(-1)
                    target = batch["reward"] + cfg.gamma \
                        * (1.0 - batch["done"]) \
                        * jax.lax.stop_gradient(v_next)
                    critic_loss = jnp.mean((q_sa - target) ** 2)
                    # advantage under the CURRENT policy's expectation
                    pi_cur, _ = jax.vmap(
                        lambda o: policy.forward(p["pi"], o))(
                            batch["obs"])
                    pi_cur = jax.nn.softmax(jax.lax.stop_gradient(pi_cur))
                    v_s = (pi_cur * jax.lax.stop_gradient(qvals)).sum(-1)
                    adv = jax.lax.stop_gradient(q_sa) - v_s
                    if cfg.weight_fn == "binary":
                        w = (adv > 0).astype(jnp.float32)
                    else:
                        w = jnp.minimum(jnp.exp(adv / cfg.beta),
                                        cfg.weight_clip)
                    logp, _, _ = jax.vmap(
                        lambda o, a: policy.log_prob(p["pi"], o, a))(
                            batch["obs"], batch["action"])
                    actor_loss = -jnp.mean(w * logp)
                    return actor_loss + critic_loss, \
                        (actor_loss, critic_loss, w.mean())

                (loss, (a_l, c_l, w_mean)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                updates, opt_state = self.optimizer.update(
                    grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                target_q = jax.tree_util.tree_map(
                    lambda t, o: (1 - cfg.tau) * t + cfg.tau * o,
                    target_q, params["q"])
                return (params, target_q, opt_state), (a_l, c_l, w_mean)

            (params, target_q, opt_state), (a_ls, c_ls, w_means) = \
                jax.lax.scan(mb_step, (params, target_q, opt_state), idx)
            return (params, target_q, opt_state, key,
                    a_ls.mean(), c_ls.mean(), w_means.mean())

        return epoch

    def training_step(self) -> Dict[str, Any]:
        a_l = c_l = w_m = None
        for _ in range(self.config.epochs_per_iter):
            (self.params, self.target_q, self.opt_state, self.key,
             a_l, c_l, w_m) = self._epoch(
                self.params, self.target_q, self.opt_state, self.key)
        return {"actor_loss": float(a_l), "critic_loss": float(c_l),
                "accepted_fraction": float(w_m),
                "env_steps_this_iter": 0}

    def action_fn(self):
        """Greedy jittable policy for deployment/eval."""
        policy, params = self.policy, self.params["pi"]

        def act(obs, key):
            pi, _ = policy.forward(params, obs)
            return jnp.argmax(pi, axis=-1)
        return act

    def get_state(self) -> Dict[str, Any]:
        to_np = lambda t: jax.tree_util.tree_map(np.asarray, t)  # noqa: E731
        return {"params": to_np(self.params),
                "target_q": to_np(self.target_q),
                "iteration": self.iteration}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = jax.tree_util.tree_map(
            lambda _, x: jnp.asarray(x), self.params, state["params"])
        self.target_q = jax.tree_util.tree_map(
            lambda _, x: jnp.asarray(x), self.target_q, state["target_q"])
        self.iteration = state.get("iteration", 0)
