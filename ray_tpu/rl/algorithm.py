"""Algorithm: the Trainable-style RL entry point.

Capability mirror of the reference's `Algorithm(Trainable)`
(`rllib/algorithms/algorithm.py:147,711`): `train()` drives
`training_step`, results accumulate standard keys, checkpoints via
`air.Checkpoint`, and `to_trainable()` plugs into Tune.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ..air.checkpoint import Checkpoint


def track_episode_returns(ep_returns: np.ndarray, done_returns: list,
                          rewards: np.ndarray,
                          dones: np.ndarray) -> None:
    """ONE definition of the reward/done episode bookkeeping, shared by
    Algorithm subclasses and out-of-process collectors (impala/apex):
    accumulate per-env returns over a [T, B] trajectory, bank each
    finished episode, zero its accumulator."""
    for t in range(rewards.shape[0]):
        ep_returns += rewards[t]
        finished = dones[t].astype(bool)
        if finished.any():
            done_returns.extend(ep_returns[finished].tolist())
            ep_returns[finished] = 0.0


class Algorithm:
    _config_cls = None

    def __init__(self, config):
        self.config = config
        self.iteration = 0
        self._total_env_steps = 0

    # -- shared episode accounting (host side, cheap) -----------------------
    def _init_episode_tracking(self, num_envs: int) -> None:
        self._ep_returns = np.zeros(num_envs)
        self._ep_done_returns: list = []

    def _track_episodes(self, rewards: np.ndarray, dones: np.ndarray):
        """Accumulate per-env returns from a [T, B] reward/done trajectory,
        banking each finished episode's return."""
        track_episode_returns(self._ep_returns, self._ep_done_returns,
                              rewards, dones)

    def episode_reward_mean(self) -> float:
        """Mean return of the last 100 finished episodes (NaN before any)."""
        if not getattr(self, "_ep_done_returns", None):
            return float("nan")
        return float(np.mean(self._ep_done_returns[-100:]))

    # -- Trainable protocol -------------------------------------------------
    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        result = self.training_step()
        self._total_env_steps += result.get("env_steps_this_iter", 0)
        result.setdefault("training_iteration", self.iteration)
        result["env_steps_total"] = self._total_env_steps
        return result

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def stop(self) -> None:
        workers = getattr(self, "_workers", None)
        if workers is not None:
            workers.stop()

    # -- checkpointing ------------------------------------------------------
    def get_state(self) -> Dict[str, Any]:
        raise NotImplementedError

    def set_state(self, state: Dict[str, Any]) -> None:
        raise NotImplementedError

    def save(self) -> Checkpoint:
        return Checkpoint.from_dict(self.get_state())

    def restore(self, checkpoint: Checkpoint) -> None:
        self.set_state(checkpoint.to_dict())

    # -- Tune integration ---------------------------------------------------
    @classmethod
    def to_trainable(cls, base_config) -> Callable:
        """A Tune function-trainable: config overrides merge into the
        algorithm config; reports every iteration with a checkpoint."""

        def trainable(config: Dict[str, Any]):
            import dataclasses

            from ..air import session
            overrides = {k: v for k, v in config.items()
                         if hasattr(base_config, k)}
            algo_cfg = dataclasses.replace(base_config, **overrides)
            stop_iters = config.get("stop_iters", 10)
            algo = cls(algo_cfg)
            ck = session.get_checkpoint()
            if ck is not None:
                algo.restore(ck)
            try:
                while algo.iteration < stop_iters:
                    result = algo.train()
                    session.report(result, checkpoint=algo.save())
            finally:
                algo.stop()

        return trainable
