"""R2D2: recurrent-replay distributed DQN.

Capability mirror of the reference's R2D2
(`rllib/algorithms/r2d2/r2d2.py` — DQN over an LSTM Q-network with a
sequence replay buffer, stored recurrent states, and burn-in).  TPU-first
shape: the buffer rows ARE fixed-length sequences (the same
device-resident circular buffer as DQN, with ``[T, ...]``-shaped leaves),
the vectorized collect scan banks one sequence per env per iteration
together with the LSTM state at its start (the paper's "stored state"
strategy), and the update unrolls burn-in + TD through ``lax.scan``
entirely on device — collection, insertion, sampling, and the recurrent
double-Q update compile into ONE XLA program, like dqn.py.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import replay
from .algorithm import Algorithm
from .env import JaxEnv
from .policy import mlp_apply, mlp_init


class RecurrentQNetwork:
    """obs → MLP torso → LSTM cell → Q[action]; explicit ``(h, c)``
    carry like LSTMPolicy (policy.py), composing with ``lax.scan``."""

    def __init__(self, obs_size: int, n_actions: int, hidden=(64,),
                 lstm_size: int = 64):
        if not hidden:
            raise ValueError("RecurrentQNetwork needs >=1 torso layer")
        self.obs_size = obs_size
        self.n_actions = n_actions
        self.hidden = tuple(hidden)
        self.lstm_size = lstm_size

    def init(self, key: jax.Array):
        kt, kl, kq = jax.random.split(key, 3)
        in_dim = self.hidden[-1] + self.lstm_size
        return {
            "torso": mlp_init(kt, (self.obs_size,) + self.hidden),
            "lstm": {"w": jax.random.normal(
                kl, (in_dim, 4 * self.lstm_size))
                * math.sqrt(1.0 / in_dim),
                "b": jnp.zeros((4 * self.lstm_size,))},
            "q": {"w": jax.random.normal(
                kq, (self.lstm_size, self.n_actions)) * 0.01,
                "b": jnp.zeros((self.n_actions,))},
        }

    def initial_state(self, batch_size: Optional[int] = None):
        shape = ((self.lstm_size,) if batch_size is None
                 else (batch_size, self.lstm_size))
        return (jnp.zeros(shape), jnp.zeros(shape))

    def step(self, params, obs: jnp.ndarray, state):
        """One timestep: obs [.., obs] + (h, c) → (q [.., A], state')."""
        x = obs
        for layer in params["torso"]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        h, c = state
        z = jnp.concatenate([x, h], axis=-1) @ params["lstm"]["w"] \
            + params["lstm"]["b"]
        i, f, g, o = jnp.split(z, 4, axis=-1)
        c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        q = h @ params["q"]["w"] + params["q"]["b"]
        return q, (h, c)

    def unroll(self, params, obs_seq: jnp.ndarray, done_seq: jnp.ndarray,
               init_state):
        """[T, B, obs] + done [T, B] (state resets AFTER a done step,
        matching the collect scan) → q_seq [T, B, A]."""

        def step_fn(state, inp):
            obs, done = inp
            q, state = self.step(params, obs, state)
            keep = (1.0 - done.astype(jnp.float32))[..., None]
            state = jax.tree_util.tree_map(lambda s: s * keep, state)
            return state, q

        _, q_seq = jax.lax.scan(step_fn, init_state, (obs_seq, done_seq))
        return q_seq


@dataclasses.dataclass
class R2D2Config:
    env: Optional[Callable[[], JaxEnv]] = None
    num_envs: int = 16
    seq_len: int = 20              # stored sequence length (after burn-in)
    burn_in: int = 4               # prefix steps that only warm the state
    buffer_capacity: int = 2048    # capacity in SEQUENCES
    batch_size: int = 32           # sequences per TD update
    num_updates: int = 8           # SGD steps per iteration
    gamma: float = 0.99
    lr: float = 1e-3
    tau: float = 0.01              # Polyak target-average rate
    double_q: bool = True
    eps_start: float = 1.0
    eps_end: float = 0.05
    eps_decay_steps: int = 20_000
    learn_start: int = 32          # sequences in buffer before updates
    hidden: tuple = (64,)
    lstm_size: int = 64
    seed: int = 0

    def build(self) -> "R2D2":
        return R2D2(self)


class R2D2(Algorithm):
    _config_cls = R2D2Config

    def __init__(self, config: R2D2Config):
        super().__init__(config)
        cfg = config
        if cfg.env is None:
            raise ValueError("R2D2Config.env required (an env factory)")
        if cfg.burn_in >= cfg.seq_len:
            raise ValueError(
                f"burn_in={cfg.burn_in} >= seq_len={cfg.seq_len}: no "
                "steps would remain for the TD loss")
        self.env = cfg.env()
        if not self.env.discrete:
            raise ValueError("R2D2 is a DQN variant: discrete actions "
                             "only")
        obs_dim, n_act = self.env.observation_size, self.env.action_size
        self.q = RecurrentQNetwork(obs_dim, n_act, hidden=cfg.hidden,
                                   lstm_size=cfg.lstm_size)
        key = jax.random.PRNGKey(cfg.seed)
        key, pkey, ekey = jax.random.split(key, 3)
        self.params = self.q.init(pkey)
        self.target_params = jax.tree_util.tree_map(lambda x: x,
                                                    self.params)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        T = cfg.seq_len
        # one row = one sequence + the LSTM state at its start; obs has
        # T+1 entries so every step's TD target has its next_obs in-row
        self.buffer = replay.init(cfg.buffer_capacity, {
            "obs": jnp.zeros((T + 1, obs_dim), jnp.float32),
            "action": jnp.zeros((T,), jnp.int32),
            "reward": jnp.zeros((T,), jnp.float32),
            "done": jnp.zeros((T,), jnp.float32),
            "h0": jnp.zeros((cfg.lstm_size,), jnp.float32),
            "c0": jnp.zeros((cfg.lstm_size,), jnp.float32),
        })
        ekeys = jax.random.split(ekey, cfg.num_envs)
        self.env_states, self.obs = jax.vmap(self.env.reset)(ekeys)
        self.lstm_state = self.q.initial_state(cfg.num_envs)
        self.key = key
        from .exploration import EpsilonGreedy
        self._explorer = EpsilonGreedy(cfg.eps_start, cfg.eps_end,
                                       cfg.eps_decay_steps)
        self._train_iter = jax.jit(self._make_train_iter())
        self._init_episode_tracking(cfg.num_envs)

    # -- the compiled iteration --------------------------------------------
    def _make_train_iter(self):
        cfg, env, q = self.config, self.env, self.q
        explorer = self._explorer
        T = cfg.seq_len
        from .learner import make_update_gate

        def td_loss(params, target_params, batch):
            """batch leaves are [B, ...] sequence rows."""
            # time-major views
            obs = jnp.swapaxes(batch["obs"], 0, 1)        # [T+1, B, obs]
            done = jnp.swapaxes(batch["done"], 0, 1)      # [T, B]
            init = (batch["h0"], batch["c0"])
            # the T+1-th unroll step needs a done flag; the final obs
            # never produces a TD target past it, so pad with zeros
            done_pad = jnp.concatenate(
                [done, jnp.zeros((1,) + done.shape[1:])], axis=0)
            q_on = q.unroll(params, obs, done_pad, init)  # [T+1, B, A]
            q_tg = q.unroll(target_params, obs, done_pad, init)
            q_sa = jnp.take_along_axis(
                q_on[:T], jnp.swapaxes(batch["action"], 0, 1)[..., None],
                axis=-1)[..., 0]                           # [T, B]
            if cfg.double_q:
                sel = jnp.argmax(q_on[1:], axis=-1)        # [T, B]
            else:
                sel = jnp.argmax(q_tg[1:], axis=-1)
            q_next = jnp.take_along_axis(
                q_tg[1:], sel[..., None], axis=-1)[..., 0]
            target = jnp.swapaxes(batch["reward"], 0, 1) + cfg.gamma \
                * (1.0 - done) * jax.lax.stop_gradient(q_next)
            td = q_sa - jax.lax.stop_gradient(target)
            # burn-in steps warm the recurrence but carry no loss
            mask = (jnp.arange(T) >= cfg.burn_in).astype(jnp.float32)
            td = td * mask[:, None]
            return (td ** 2).sum() / (mask.sum() * td.shape[1])

        update_gate = make_update_gate(
            self.optimizer, tau=cfg.tau, learn_start=cfg.learn_start,
            num_updates=cfg.num_updates,
            sample_fn=lambda buf, key: replay.sample(buf, key,
                                                     cfg.batch_size),
            loss_fn=td_loss)

        def train_iter(params, target_params, opt_state, buffer,
                       env_states, obs, lstm_state, key, total_steps):
            h0, c0 = lstm_state                            # state at seq start

            def collect(carry, _):
                env_states, obs, lstm_state, key = carry
                key, akey, skey = jax.random.split(key, 3)
                qvals, lstm_state = q.step(params, obs, lstm_state)
                _, action = explorer((), akey, qvals, total_steps)
                skeys = jax.random.split(skey, cfg.num_envs)
                env_states, next_obs, reward, done = jax.vmap(env.step)(
                    env_states, action, skeys)
                keep = (1.0 - done.astype(jnp.float32))[..., None]
                lstm_state = jax.tree_util.tree_map(
                    lambda s: s * keep, lstm_state)
                frame = {"obs": obs.astype(jnp.float32),
                         "action": action.astype(jnp.int32),
                         "reward": reward.astype(jnp.float32),
                         "done": done.astype(jnp.float32)}
                return (env_states, next_obs, lstm_state, key), frame

            (env_states, obs, lstm_state, key), traj = jax.lax.scan(
                collect, (env_states, obs, lstm_state, key), None,
                length=T)
            # bank one sequence per env, batch-major rows with the final
            # observation appended
            obs_rows = jnp.concatenate(
                [jnp.swapaxes(traj["obs"], 0, 1), obs[:, None]], axis=1)
            buffer = replay.add_batch(buffer, {
                "obs": obs_rows,
                "action": jnp.swapaxes(traj["action"], 0, 1),
                "reward": jnp.swapaxes(traj["reward"], 0, 1),
                "done": jnp.swapaxes(traj["done"], 0, 1),
                "h0": h0, "c0": c0,
            }, cfg.num_envs)

            (params, target_params, opt_state, buffer, key,
             last_loss) = update_gate(params, target_params, opt_state,
                                      buffer, key)
            metrics = {"td_loss": last_loss,
                       "epsilon": explorer.epsilon(total_steps),
                       "buffer_size": buffer["size"]}
            return (params, target_params, opt_state, buffer, env_states,
                    obs, lstm_state, key, metrics, traj["reward"],
                    traj["done"])

        return train_iter

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        (self.params, self.target_params, self.opt_state, self.buffer,
         self.env_states, self.obs, self.lstm_state, self.key, metrics,
         rewards, dones) = self._train_iter(
            self.params, self.target_params, self.opt_state, self.buffer,
            self.env_states, self.obs, self.lstm_state, self.key,
            jnp.asarray(self._total_env_steps, jnp.float32))
        self._track_episodes(np.asarray(rewards), np.asarray(dones))
        dt = time.perf_counter() - t0
        steps = cfg.num_envs * cfg.seq_len
        return {
            "td_loss": float(metrics["td_loss"]),
            "epsilon": float(metrics["epsilon"]),
            "buffer_size": int(metrics["buffer_size"]),
            "episode_reward_mean": self.episode_reward_mean(),
            "env_steps_this_iter": steps,
            "env_steps_per_s": steps / dt,
        }

    # -- checkpointing ------------------------------------------------------
    def get_state(self) -> Dict[str, Any]:
        to_np = lambda t: jax.tree_util.tree_map(np.asarray, t)  # noqa: E731
        return {"params": to_np(self.params),
                "target_params": to_np(self.target_params),
                "iteration": self.iteration,
                "env_steps_total": self._total_env_steps}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = jax.tree_util.tree_map(
            lambda _, x: jnp.asarray(x), self.params, state["params"])
        self.target_params = jax.tree_util.tree_map(
            lambda _, x: jnp.asarray(x), self.target_params,
            state["target_params"])
        self.iteration = state.get("iteration", 0)
        self._total_env_steps = state.get("env_steps_total", 0)
