"""Decentralized-DP PPO: every device is a learner, no driver SGD.

Capability mirror of the reference's DDPPO
(`rllib/algorithms/ddppo/ddppo.py:270` — workers compute gradients locally
and allreduce them via torch distributed; the driver never touches a
sample batch).  TPU-native answer: ONE `shard_map` program over a "dp"
mesh axis where each device rolls out its own vectorized envs, computes
GAE, and runs the epoch/minibatch SGD with `jax.lax.pmean` gradient
sync before every apply — params stay bit-identical across devices and
rollout + learn is a single XLA program, so "no driver SGD" is literal:
the host only dispatches the compiled iteration.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from .algorithm import Algorithm
from .policy import MLPPolicy
from .ppo import PPOConfig, compute_gae, make_rollout_fn, make_update_fn


@dataclasses.dataclass
class DDPPOConfig(PPOConfig):
    num_learners: Optional[int] = None  # None → every visible device

    def build(self) -> "DDPPO":
        return DDPPO(self)


class DDPPO(Algorithm):
    """num_envs is PER LEARNER; global batch = learners*num_envs*rollout."""

    _config_cls = DDPPOConfig

    def __init__(self, config: DDPPOConfig):
        super().__init__(config)
        cfg = config
        if cfg.env is None:
            raise ValueError("DDPPOConfig.env required (an env factory)")
        if cfg.num_workers:
            raise ValueError(
                "DDPPO has no rollout-worker actors: every mesh device is "
                "a learner+sampler (set num_learners, not num_workers)")
        from ..parallel.mesh import default_devices
        devices = default_devices()
        n = cfg.num_learners or len(devices)
        if n > len(devices):
            raise ValueError(f"num_learners={n} > {len(devices)} devices")
        self.num_learners = n
        self.mesh = Mesh(np.asarray(devices[:n]), ("dp",))

        self.env = cfg.env()
        if (cfg.model or {}).get("use_lstm"):
            raise ValueError("use_lstm is not supported by DDPPO: its "
                             "per-device learners are feedforward-only "
                             "(use PPO's local path for recurrence)")
        self.policy = MLPPolicy(self.env.observation_size,
                                self.env.action_size,
                                discrete=self.env.discrete,
                                hidden=cfg.hidden)
        key = jax.random.PRNGKey(cfg.seed)
        key, pkey, ekey = jax.random.split(key, 3)
        self.params = self.policy.init(pkey)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.max_grad_norm),
            optax.adam(cfg.lr))
        self.opt_state = self.optimizer.init(self.params)

        # global env state: leading axis n*num_envs, sharded over dp
        total_envs = n * cfg.num_envs
        ekeys = jax.random.split(ekey, total_envs)
        env_states, obs = jax.vmap(self.env.reset)(ekeys)
        shard = NamedSharding(self.mesh, P("dp"))
        repl = NamedSharding(self.mesh, P())
        self.env_states = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, shard), env_states)
        self.obs = jax.device_put(obs, shard)
        self.keys = jax.device_put(jax.random.split(key, n), shard)
        self.params = jax.device_put(self.params, repl)
        self.opt_state = jax.device_put(self.opt_state, repl)

        self._train_iter = self._build_train_iter()
        self._init_episode_tracking(total_envs)

    def _build_train_iter(self):
        cfg = self.config
        local_batch = cfg.num_envs * cfg.rollout_length
        rollout = make_rollout_fn(self.env, self.policy, cfg.num_envs,
                                  cfg.rollout_length,
                                  env_chunk=cfg.env_chunk)
        update = make_update_fn(self.policy, self.optimizer, cfg,
                                local_batch, axis_name="dp")
        discrete = self.env.discrete

        def body(params, opt_state, env_states, obs, keys):
            key = keys[0]
            traj, env_states, obs, _, last_value, key = rollout(
                params, env_states, obs, (), key)
            adv, ret = compute_gae(traj, last_value, cfg.gamma,
                                   cfg.gae_lambda)
            flat = {
                "obs": traj["obs"].reshape(local_batch, -1),
                "action": traj["action"].reshape(
                    (local_batch,) if discrete else (local_batch, -1)),
                "logp": traj["logp"].reshape(local_batch),
                "adv": adv.reshape(local_batch),
                "ret": ret.reshape(local_batch),
            }
            params, opt_state, key, metrics = update(
                params, opt_state, flat, key)
            # params are identical across dp after pmean'd grads; metrics
            # are averaged so every device reports the same numbers
            metrics = jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, "dp"), metrics)
            metrics["reward_sum"] = jax.lax.psum(traj["reward"].sum(), "dp")
            return (params, opt_state, env_states, obs, key[None],
                    metrics, traj["reward"], traj["done"])

        repl = P()
        sh = P("dp")
        state_specs = jax.tree_util.tree_map(lambda _: sh, self.env_states)
        fn = jax.shard_map(
            body, mesh=self.mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: repl, self.params),
                      jax.tree_util.tree_map(lambda _: repl,
                                             self.opt_state),
                      state_specs, sh, sh),
            out_specs=(jax.tree_util.tree_map(lambda _: repl, self.params),
                       jax.tree_util.tree_map(lambda _: repl,
                                              self.opt_state),
                       state_specs, sh, sh,
                       repl, P(None, "dp"), P(None, "dp")))
        return jax.jit(fn)

    # -- Trainable interface ------------------------------------------------
    def training_step(self) -> Dict[str, Any]:
        import time
        cfg = self.config
        t0 = time.perf_counter()
        (self.params, self.opt_state, self.env_states, self.obs,
         self.keys, metrics, rewards, dones) = self._train_iter(
            self.params, self.opt_state, self.env_states, self.obs,
            self.keys)
        metrics = {k: float(v) for k, v in metrics.items()}
        dt = time.perf_counter() - t0
        env_steps = self.num_learners * cfg.num_envs * cfg.rollout_length
        self._track_episodes(np.asarray(rewards), np.asarray(dones))
        metrics.update({
            "env_steps_this_iter": env_steps,
            "env_steps_per_s": env_steps / dt,
            "episode_reward_mean": self.episode_reward_mean(),
            "num_learners": self.num_learners,
        })
        return metrics

    # -- checkpointing ------------------------------------------------------
    def get_state(self) -> Dict[str, Any]:
        return {"params": self.policy.get_weights(self.params),
                "iteration": self.iteration}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = self.policy.set_weights(self.params, state["params"])
        self.iteration = state.get("iteration", 0)
