"""Connectors: composable obs/action transform pipelines.

Capability mirror of the reference's connector framework
(`rllib/connectors/connector.py`, `agent/obs_preproc.py`,
`action/clip.py` — pluggable transforms between env and policy,
checkpointable with the policy).  Redesigned for the TPU rollout model:
a connector here is a PURE function pair — ``init_state()`` builds a
pytree, ``__call__(state, x) -> (state, x)`` is jit-traceable — so the
whole pipeline composes INTO the `lax.scan` rollout instead of running
as a per-step Python loop beside it.  State (running moments, stacked
frames) is carried functionally through the scan like env state.
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp

State = Any


class Connector:
    """One transform.  Stateless connectors return () from init_state.

    ``kind`` declares what the transform applies to — "obs", "action",
    or "reward" — so configs can validate placement (an action clipper
    in an obs pipeline would silently distort observations otherwise).
    ``reset_on_done`` marks state that must clear at episode boundaries
    (FrameStack's ring) vs state that must persist across them
    (ObsNormalizer's running moments)."""

    kind = "obs"
    reset_on_done = False

    def init_state(self) -> State:
        return ()

    def __call__(self, state: State, x: jnp.ndarray
                 ) -> Tuple[State, jnp.ndarray]:
        raise NotImplementedError

    def out_size(self, in_size: int) -> int:
        """Observation size after this transform (for model building)."""
        return in_size


class ObsNormalizer(Connector):
    """Running mean/std normalization (reference:
    `rllib/connectors/agent/mean_std_filter.py`): Welford moments carried
    as pipeline state, updated online inside the rollout scan."""

    def __init__(self, size: int, clip: float = 10.0,
                 epsilon: float = 1e-8):
        self.size = size
        self.clip = clip
        self.epsilon = epsilon

    def init_state(self) -> State:
        return {"mean": jnp.zeros((self.size,)),
                "m2": jnp.ones((self.size,)),
                "count": jnp.ones(())}

    def __call__(self, state, x):
        # batched Welford update over the leading axis
        batch = x.reshape((-1, self.size))
        n = batch.shape[0]
        b_mean = batch.mean(axis=0)
        b_var = batch.var(axis=0)
        count = state["count"] + n
        delta = b_mean - state["mean"]
        mean = state["mean"] + delta * n / count
        m2 = state["m2"] + b_var * n + \
            delta ** 2 * state["count"] * n / count
        new = {"mean": mean, "m2": m2, "count": count}
        std = jnp.sqrt(m2 / count + self.epsilon)
        out = jnp.clip((x - mean) / std, -self.clip, self.clip)
        return new, out


class FrameStack(Connector):
    """Stack the last k observations (reference: Atari framestacking in
    the connector/preprocessor stack); the ring lives in pipeline state."""

    reset_on_done = True   # a fresh episode must not see dead frames

    def __init__(self, size: int, k: int = 4):
        self.size = size
        self.k = k

    def init_state(self) -> State:
        return jnp.zeros((self.k, self.size))

    def __call__(self, state, x):
        # x: [..., size]; state: [k, size] per logical stream — for
        # vectorized envs wrap the pipeline in vmap (see make_pipeline)
        new = jnp.concatenate([state[1:], x[None, :]], axis=0)
        return new, new.reshape(-1)

    def out_size(self, in_size: int) -> int:
        return in_size * self.k


class ClipReward(Connector):
    """Reward clipping (reference: `rllib/connectors/agent/clip.py`)."""

    kind = "reward"

    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, state, x):
        return state, jnp.clip(x, self.low, self.high)


class ClipActions(Connector):
    """Clip continuous actions into the env bound (reference:
    `rllib/connectors/action/clip.py`)."""

    kind = "action"

    def __init__(self, high: float = 1.0):
        self.high = high

    def __call__(self, state, x):
        return state, jnp.clip(x, -self.high, self.high)


class UnsquashActions(Connector):
    """Map tanh-squashed [-1, 1] policy outputs onto the env's action
    interval (reference: `rllib/connectors/action/normalize.py`)."""

    kind = "action"

    def __init__(self, high: float = 1.0):
        self.high = high

    def __call__(self, state, x):
        return state, jnp.tanh(x) * self.high


class ConnectorPipeline:
    """Ordered composition; state is the tuple of member states
    (reference: ConnectorPipeline v2).  Jit/scan-safe."""

    def __init__(self, connectors: Sequence[Connector]):
        self.connectors = list(connectors)

    def init_state(self) -> Tuple:
        return tuple(c.init_state() for c in self.connectors)

    def __call__(self, state: Tuple, x: jnp.ndarray
                 ) -> Tuple[Tuple, jnp.ndarray]:
        new_states: List[State] = []
        for c, s in zip(self.connectors, state):
            s, x = c(s, x)
            new_states.append(s)
        return tuple(new_states), x

    def out_size(self, in_size: int) -> int:
        for c in self.connectors:
            in_size = c.out_size(in_size)
        return in_size

    def validate_kind(self, kind: str, where: str) -> "ConnectorPipeline":
        bad = [type(c).__name__ for c in self.connectors
               if c.kind != kind]
        if bad:
            raise ValueError(
                f"{where} accepts only {kind!r} connectors; {bad} "
                f"belong in the "
                f"{'action_connectors' if kind == 'obs' else 'connectors'}"
                " list")
        return self

    def reset_where(self, state: Tuple, done: jnp.ndarray) -> Tuple:
        """Reset per-env state slices where ``done`` — only for members
        with ``reset_on_done`` (FrameStack rings clear at episode
        boundaries; ObsNormalizer moments persist).  ``state`` leaves
        carry a leading [num_envs] axis (init_state_batch layout)."""
        out = []
        for c, s in zip(self.connectors, state):
            if not c.reset_on_done:
                out.append(s)
                continue
            init = c.init_state()

            def mask(leaf, init_leaf):
                d = done.reshape((-1,) + (1,) * (leaf.ndim - 1))
                return jnp.where(d.astype(bool), init_leaf, leaf)

            out.append(jax.tree_util.tree_map(mask, s, init))
        return tuple(out)

    def vmapped(self, num_envs: int):
        """(states, batch_x) -> (states, batch_y) over vectorized envs;
        use inside rollout scans.  init via init_state_batch."""
        fn = jax.vmap(self.__call__)
        return fn

    def init_state_batch(self, num_envs: int) -> Tuple:
        return jax.tree_util.tree_map(
            lambda s: jnp.broadcast_to(s, (num_envs,) + s.shape)
            if hasattr(s, "shape") else s,
            self.init_state())
