"""Shared off-policy learner scaffolding.

The sample → TD-grad → optimizer step → Polyak target-average loop behind
a learn-start gate is the same compiled structure in every value-based
algorithm here (dqn.py pioneered it; R2D2 and QMIX reuse it through this
helper instead of re-pasting the scan/cond scaffolding).  The reference
spreads this across per-algorithm execution plans
(`rllib/execution/train_ops.py`); under jit it is one reusable
closure."""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax


def make_update_gate(optimizer, *, tau: float, learn_start: int,
                     num_updates: int,
                     sample_fn: Callable,
                     loss_fn: Callable):
    """→ ``gate(params, target_params, opt_state, buffer, key)`` running
    ``num_updates`` TD steps behind the learn-start gate (a no-op until
    the buffer holds ``learn_start`` rows), Polyak-averaging the target
    after every step.

    ``sample_fn(buffer, key) -> (batch, idx, key)``;
    ``loss_fn(params, target_params, batch) -> scalar loss``.
    Returns ``(params, target_params, opt_state, buffer, key,
    last_loss)``."""

    def update(carry, _):
        params, target_params, opt_state, buffer, key = carry
        batch, _, key = sample_fn(buffer, key)
        loss, grads = jax.value_and_grad(loss_fn)(
            params, target_params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        target_params = jax.tree_util.tree_map(
            lambda t, p: (1 - tau) * t + tau * p, target_params, params)
        return (params, target_params, opt_state, buffer, key), loss

    def run_updates(args):
        (params, target_params, opt_state, buffer, key), losses = \
            jax.lax.scan(update, args, None, length=num_updates)
        return (params, target_params, opt_state, buffer, key,
                losses[-1])

    def skip_updates(args):
        params, target_params, opt_state, buffer, key = args
        return (params, target_params, opt_state, buffer, key,
                jnp.zeros(()))

    def gate(params, target_params, opt_state, buffer, key):
        return jax.lax.cond(
            buffer["size"] >= learn_start, run_updates, skip_updates,
            (params, target_params, opt_state, buffer, key))

    return gate
