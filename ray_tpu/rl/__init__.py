"""Reinforcement learning on the distributed runtime.

Capability mirror of the reference's `rllib/` core (SURVEY.md §3.6:
`Algorithm(Trainable)` with `training_step`, `RolloutWorker` actors +
`WorkerSet`, `Policy` abstraction, vectorized envs) — redesigned TPU-first:
environments are pure-JAX functions, so rollout + GAE + PPO update compile
into ONE XLA program (`lax.scan` over env steps); the reference's
embryonic JAX policy path (`rllib/policy/policy_template.py:38`,
`rllib/models/jax/`) becomes the only path.  External (host) envs are
supported through rollout-worker actors like the reference's sampler.
"""

from .algorithm import Algorithm  # noqa: F401
from .a3c import A3C, A3CConfig  # noqa: F401
from .alpha_zero import AlphaZero, AlphaZeroConfig, TicTacToe  # noqa: F401
from .apex import (  # noqa: F401
    ApexDDPG,
    ApexDDPGConfig,
    ApexDQN,
    ApexDQNConfig,
    collector_epsilon,
    collector_noise_scale,
)
from .bandit import (  # noqa: F401
    ContextBandit,
    LinearContextBandit,
    LinTS,
    LinTSConfig,
    LinUCB,
    LinUCBConfig,
)
from .dqn import (  # noqa: F401
    DQN,
    DQNConfig,
    QNetwork,
    Rainbow,
    RainbowConfig,
    SimpleQ,
    SimpleQConfig,
)
from .pg import PG, PGConfig  # noqa: F401
from .dreamer import Dreamer, DreamerConfig  # noqa: F401
from .dt import DT, DTConfig  # noqa: F401
from .maml import MAML, MAMLConfig  # noqa: F401
from .maddpg import (  # noqa: F401
    MADDPG,
    MADDPGConfig,
    SpreadLineContinuous,
)
from .qmix import QMIX, QMIXConfig  # noqa: F401
from .r2d2 import R2D2, R2D2Config, RecurrentQNetwork  # noqa: F401
from .env import (  # noqa: F401
    CartPole,
    GridTarget,
    JaxEnv,
    MemoryCue,
    Pendulum,
    PixelPong,
)
from .es import ARS, ARSConfig, ES, ESConfig  # noqa: F401
from .impala import APPOConfig, Impala, ImpalaConfig  # noqa: F401
from .sac import SAC, SACConfig  # noqa: F401
from .slateq import RecSlateEnv, SlateQ, SlateQConfig  # noqa: F401
from .td3 import DDPG, DDPGConfig, TD3, TD3Config  # noqa: F401
from .offline import (  # noqa: F401
    BC,
    BCConfig,
    CQL,
    CQLConfig,
    CRR,
    CRRConfig,
    MARWIL,
    MARWILConfig,
    collect_dataset,
    importance_sampling_estimate,
    load_dataset,
    save_dataset,
)
from .multi_agent import (  # noqa: F401
    IndependentPPO,
    IndependentPPOConfig,
    MultiAgentJaxEnv,
    SpreadLine,
)
from .catalog import build_policy, register_custom_model  # noqa: F401
from .connectors import (  # noqa: F401
    ClipActions,
    ClipReward,
    Connector,
    ConnectorPipeline,
    FrameStack,
    ObsNormalizer,
    UnsquashActions,
)
from .ddppo import DDPPO, DDPPOConfig  # noqa: F401
from .external import (  # noqa: F401
    ExternalEnv,
    PolicyClient,
    PolicyServerInput,
)
from .exploration import (  # noqa: F401
    EpsilonGreedy,
    GaussianActionNoise,
    OrnsteinUhlenbeckNoise,
    StochasticSampling,
)
from .policy import ConvPolicy, LSTMPolicy, MLPPolicy  # noqa: F401
from .ppo import A2CConfig, PPO, PPOConfig  # noqa: F401
from .rollout_worker import RolloutWorker  # noqa: F401
from .worker_set import WorkerSet  # noqa: F401
