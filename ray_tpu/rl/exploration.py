"""Exploration strategies as pure jittable functions.

Capability mirror of the reference's exploration module zoo
(`rllib/utils/exploration/epsilon_greedy.py`, `ornstein_uhlenbeck.py`,
`gaussian_noise.py`, `stochastic_sampling.py`).  Each strategy is a
(schedule, state-transition) pair with no Python-side mutation: state is
a pytree threaded through the rollout scan, timestep-dependent schedules
are closed-form so the whole anneal traces into one XLA program.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

State = Any


class EpsilonGreedy:
    """Annealed epsilon-greedy over Q-values/logits (reference:
    epsilon_greedy.py PiecewiseSchedule)."""

    def __init__(self, eps_start: float = 1.0, eps_end: float = 0.05,
                 decay_steps: int = 20_000):
        self.eps_start = eps_start
        self.eps_end = eps_end
        self.decay_steps = decay_steps

    def epsilon(self, timestep: jnp.ndarray) -> jnp.ndarray:
        frac = jnp.clip(timestep / self.decay_steps, 0.0, 1.0)
        return self.eps_start + frac * (self.eps_end - self.eps_start)

    def init_state(self) -> State:
        return ()

    def __call__(self, state: State, key: jax.Array, qvals: jnp.ndarray,
                 timestep: jnp.ndarray) -> Tuple[State, jnp.ndarray]:
        """qvals: [..., actions] -> (state, action)."""
        k_choice, k_rand = jax.random.split(key)
        greedy = jnp.argmax(qvals, axis=-1)
        random = jax.random.randint(k_rand, greedy.shape, 0,
                                    qvals.shape[-1])
        explore = jax.random.uniform(k_choice, greedy.shape) < \
            self.epsilon(timestep)
        return state, jnp.where(explore, random, greedy)


class GaussianActionNoise:
    """Additive annealed Gaussian noise on continuous actions
    (reference: gaussian_noise.py)."""

    def __init__(self, scale_start: float = 0.3, scale_end: float = 0.05,
                 decay_steps: int = 20_000, clip: float = 1.0):
        self.scale_start = scale_start
        self.scale_end = scale_end
        self.decay_steps = decay_steps
        self.clip = clip

    def scale(self, timestep: jnp.ndarray) -> jnp.ndarray:
        frac = jnp.clip(timestep / self.decay_steps, 0.0, 1.0)
        return self.scale_start + frac * (self.scale_end -
                                          self.scale_start)

    def init_state(self) -> State:
        return ()

    def __call__(self, state, key, action, timestep):
        noise = jax.random.normal(key, action.shape) * \
            self.scale(timestep)
        return state, jnp.clip(action + noise, -self.clip, self.clip)


class OrnsteinUhlenbeckNoise:
    """Temporally-correlated OU noise for continuous control
    (reference: ornstein_uhlenbeck.py); the OU process state rides the
    rollout scan."""

    def __init__(self, action_size: int, theta: float = 0.15,
                 sigma: float = 0.2, dt: float = 1e-2, clip: float = 1.0):
        self.action_size = action_size
        self.theta = theta
        self.sigma = sigma
        self.dt = dt
        self.clip = clip

    def init_state(self) -> State:
        return jnp.zeros((self.action_size,))

    def __call__(self, state, key, action, timestep):
        noise = state + self.theta * (-state) * self.dt + \
            self.sigma * jnp.sqrt(self.dt) * \
            jax.random.normal(key, state.shape)
        return noise, jnp.clip(action + noise, -self.clip, self.clip)


class StochasticSampling:
    """Sample from the policy distribution itself — the default for
    PG-family algorithms (reference: stochastic_sampling.py).
    ``discrete=True``: input is logits, output a categorical sample;
    ``discrete=False``: input is an already-sampled continuous action,
    passed through unchanged.  The space is DECLARED, not guessed —
    both inputs are float arrays, so a dtype heuristic would silently
    turn continuous actions into categorical indices."""

    def __init__(self, discrete: bool = True):
        self.discrete = discrete

    def init_state(self) -> State:
        return ()

    def __call__(self, state, key, logits_or_action, timestep):
        if self.discrete:
            return state, jax.random.categorical(key, logits_or_action)
        return state, logits_or_action
