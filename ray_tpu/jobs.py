"""Job submission: run driver scripts against the cluster.

Capability mirror of the reference's job submission
(`dashboard/modules/job/job_manager.py`, `sdk.py:40,125` — submit an
entrypoint command, track status, fetch logs).  Jobs run as detached
subprocesses with stdout/stderr captured to a log file; status persists in
the controller KV so any client can query it.
"""

from __future__ import annotations

import json
import os
import subprocess
import tempfile
import time
import uuid
from typing import Any, Dict, List, Optional

from .api import _ensure_initialized

_NS = "jobs"

PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"


def _kv(core):
    return core.controller


def _put(core, job_id: str, info: Dict[str, Any]) -> None:
    _kv(core).call("kv_put", {"ns": _NS, "key": job_id.encode(),
                              "value": json.dumps(info).encode()})


def _get(core, job_id: str) -> Optional[Dict[str, Any]]:
    raw = _kv(core).call("kv_get", {"ns": _NS, "key": job_id.encode()})
    return json.loads(raw.decode()) if raw else None


def submit_job(entrypoint: str, *,
               runtime_env: Optional[Dict[str, Any]] = None,
               submission_id: Optional[str] = None) -> str:
    """Launch the entrypoint shell command; returns the job id."""
    core = _ensure_initialized()
    job_id = submission_id or f"job_{uuid.uuid4().hex[:10]}"
    log_dir = os.path.join(tempfile.gettempdir(), "ray_tpu_jobs")
    os.makedirs(log_dir, exist_ok=True)
    log_path = os.path.join(log_dir, f"{job_id}.log")
    from .core.node import _child_env
    env = _child_env()  # strips TPU-claim vars in hermetic CPU mode
    env["RAY_TPU_ADDRESS"] = core.controller_addr
    # init(address="auto") inside the job needs the local nodelet too
    env["RAY_TPU_NODELET"] = core.nodelet_addr
    env["RAY_TPU_SESSION_DIR"] = core.session_dir
    env["RAY_TPU_JOB_ID"] = job_id
    for k, v in (runtime_env or {}).get("env_vars", {}).items():
        env[k] = str(v)
    if "working_dir" in (runtime_env or {}):
        cwd = runtime_env["working_dir"]
    else:
        cwd = os.getcwd()
    log_f = open(log_path, "wb")
    proc = subprocess.Popen(entrypoint, shell=True, stdout=log_f,
                            stderr=subprocess.STDOUT, env=env, cwd=cwd,
                            start_new_session=True)
    _put(core, job_id, {"status": RUNNING, "pid": proc.pid,
                        "entrypoint": entrypoint, "log_path": log_path,
                        "start_time": time.time()})
    import threading

    def reap():
        code = proc.wait()
        log_f.close()
        info = _get(core, job_id) or {}
        info.update(status=SUCCEEDED if code == 0 else FAILED,
                    returncode=code, end_time=time.time())
        try:
            _put(core, job_id, info)
        except Exception:
            pass

    threading.Thread(target=reap, daemon=True).start()
    return job_id


def get_job_status(job_id: str) -> Optional[str]:
    info = _get(_ensure_initialized(), job_id)
    return info["status"] if info else None


def get_job_info(job_id: str) -> Optional[Dict[str, Any]]:
    return _get(_ensure_initialized(), job_id)


def get_job_logs(job_id: str) -> str:
    info = _get(_ensure_initialized(), job_id)
    if not info:
        raise ValueError(f"unknown job {job_id}")
    try:
        with open(info["log_path"], "r", errors="replace") as f:
            return f.read()
    except FileNotFoundError:
        return ""


def wait_job(job_id: str, timeout_s: float = 300.0) -> str:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        st = get_job_status(job_id)
        if st in (SUCCEEDED, FAILED):
            return st
        time.sleep(0.2)
    raise TimeoutError(f"job {job_id} still {get_job_status(job_id)}")


def list_jobs() -> List[Dict[str, Any]]:
    core = _ensure_initialized()
    keys = _kv(core).call("kv_keys", {"ns": _NS, "prefix": b""})
    out = []
    for k in keys:
        info = _get(core, k.decode() if isinstance(k, bytes) else k)
        if info:
            info["job_id"] = k.decode() if isinstance(k, bytes) else k
            out.append(info)
    return out


def stop_job(job_id: str) -> bool:
    info = _get(_ensure_initialized(), job_id)
    if not info or info["status"] != RUNNING:
        return False
    import signal
    try:
        os.killpg(os.getpgid(info["pid"]), signal.SIGTERM)
        return True
    except ProcessLookupError:
        return False
