"""Dataset: lazy task-parallel transforms over object-store block refs.

Capability mirror of the reference's `data/dataset.py:323` (map_batches and
friends), `_internal/plan.py:74` (lazy plan + stage fusion),
`_internal/push_based_shuffle.py:330` (2-stage shuffle).  Transforms record
stages on an ExecutionPlan; at execution, chained map-family stages fuse
into ONE task per block, and all-to-all ops (repartition/shuffle/sort) run
the two-stage map/merge pattern so no single process materializes the
dataset.
"""

from __future__ import annotations

import builtins
import itertools
import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np

from .. import api
from .block import Block, BlockAccessor, BlockMetadata, batch_to_block

# lazily-created remote helpers (need an initialized runtime)
_REMOTES: Dict[str, Any] = {}


def _remote(name: str, fn: Callable, num_returns: int = 1):
    key = f"{name}/{num_returns}"
    if key not in _REMOTES:
        _REMOTES[key] = api.remote(num_returns=num_returns)(fn)
    return _REMOTES[key]


class ActorPoolStrategy:
    """Compute strategy for map_batches: a pool of ``size`` long-lived
    actors (reference: `data.ActorPoolStrategy` — the stateful
    batch-inference path)."""

    def __init__(self, size: int = 2):
        if size < 1:
            raise ValueError("ActorPoolStrategy size must be >= 1")
        self.size = size


def _map_block_batches(fn: Callable, block: "Block", batch_size,
                       batch_format: str) -> "Block":
    """ONE definition of the slice→fn→recombine loop, shared by the
    task path and the actor path."""
    acc = BlockAccessor(block)
    rows = acc.num_rows()
    size = batch_size or max(rows, 1)
    outs = []
    for start in builtins.range(0, max(rows, 1), size):
        piece = BlockAccessor(acc.slice(start, min(start + size, rows)))
        res = fn(piece.to_batch(batch_format))
        outs.append(batch_to_block(res))
    return BlockAccessor.combine(outs) if outs else block


class _BatchMapWorker:
    """Actor body for map_batches(compute=ActorPoolStrategy): a
    callable CLASS instantiates ONCE here (the load-model-once
    contract); plain functions pass through."""

    def __init__(self, fn_blob: bytes):
        from ..core.serialization import loads_function
        fn = loads_function(fn_blob)
        self._fn = fn() if isinstance(fn, type) else fn

    def map_block(self, block, batch_size, batch_format):
        out = _map_block_batches(self._fn, block, batch_size,
                                 batch_format)
        return out, BlockAccessor(out).metadata()


# -- task bodies (top-level, cloudpickled once each) ------------------------


def _bernoulli_sample_block(block: Block, idx: int, seed,
                            fraction) -> Block:
    """Bernoulli row sample of one block; seeded PER BLOCK — one shared
    stream would apply the same positional keep-mask to every block
    (N copies of one pattern, not a sample)."""
    rng = np.random.default_rng(None if seed is None else (seed, idx))
    acc = BlockAccessor(block)
    keep = np.nonzero(rng.random(acc.num_rows()) < fraction)[0]
    return acc.take(list(keep))

def _split_block(block: Block, n: int, how: str, seed: Optional[int],
                 part_index: int) -> List[Block]:
    acc = BlockAccessor(block)
    rows = acc.num_rows()
    if how == "shuffle":
        rng = np.random.default_rng(None if seed is None
                                    else seed + part_index)
        assignment = rng.integers(0, n, size=rows)
    else:  # contiguous split for repartition
        assignment = np.repeat(np.arange(n),
                               np.diff(np.linspace(0, rows, n + 1)
                                       .astype(int)))
    return [acc.take(list(np.nonzero(assignment == i)[0]))
            for i in range(n)]


def _merge_blocks(shuffle_seed, *parts: Block) -> Tuple[Block, BlockMetadata]:
    merged = BlockAccessor.combine(list(parts))
    if shuffle_seed is not None:
        acc = BlockAccessor(merged)
        rng = np.random.default_rng(shuffle_seed)
        merged = acc.take(list(rng.permutation(acc.num_rows())))
    return merged, BlockAccessor(merged).metadata()


def _sort_partition(block: Block, key: Optional[str], boundaries: List[Any],
                    descending: bool) -> List[Block]:
    acc = BlockAccessor(block)
    rows = list(acc.iter_rows())
    vals = [r[key] if key else r for r in rows]
    order = np.argsort(np.asarray(vals, dtype=object), kind="stable")
    parts: List[List[int]] = [[] for _ in builtins.range(
        len(boundaries) + 1)]
    for i in order:
        v = vals[i]
        j = np.searchsorted(np.asarray(boundaries, dtype=object), v,
                            side="right")
        parts[int(j)].append(int(i))
    # partitions stay in ascending boundary order; the driver reverses the
    # partition iteration for descending sorts
    return [acc.take(p) for p in parts]


def _sort_merge(key: Optional[str], descending: bool,
                *parts: Block) -> Tuple[Block, BlockMetadata]:
    merged = BlockAccessor.combine(list(parts))
    acc = BlockAccessor(merged)
    rows = list(acc.iter_rows())
    vals = [r[key] if key else r for r in rows]
    order = list(np.argsort(np.asarray(vals, dtype=object), kind="stable"))
    if descending:
        order = order[::-1]
    out = acc.take([int(i) for i in order])
    return out, BlockAccessor(out).metadata()


def _get_meta(block: Block) -> BlockMetadata:
    return BlockAccessor(block).metadata()


def _sample_block(block: Block, n: int, key: Optional[str]) -> List[Any]:
    return BlockAccessor(block).sample(n, key)


# -- all-to-all executors (driver-side, run inside AllToAllStage.fn) --------

def _exec_two_stage(refs: List[Any], n_out: int, how: str,
                    seed: Optional[int]):
    merge = _remote("merge", _merge_blocks, num_returns=2)
    if n_out == 1:
        pair = merge.remote(seed if how == "shuffle" else None, *refs)
        return [pair[0]], [api.get(pair[1], timeout=600.0)]
    split = _remote(f"split/{n_out}", _split_block, num_returns=n_out)
    parts = [split.remote(b, n_out, how, seed, i)
             for i, b in enumerate(refs)]
    out_refs, out_meta_refs = [], []
    for j in builtins.range(n_out):
        seed_j = None if seed is None else seed + 1000003 * j
        pair = merge.remote(seed_j if how == "shuffle" else None,
                            *[p[j] for p in parts])
        out_refs.append(pair[0])
        out_meta_refs.append(pair[1])
    return out_refs, api.get(out_meta_refs, timeout=600.0)


def _exec_sort(refs: List[Any], meta: List[BlockMetadata],
               key: Optional[str], descending: bool):
    n = max(len(refs), 1)
    sampler = _remote("sample", _sample_block)
    samples: List[Any] = []
    for chunk in api.get([sampler.remote(b, 16, key) for b in refs],
                         timeout=600.0):
        samples.extend(chunk)
    if not samples:
        return refs, meta
    merge = _remote("sortmerge", _sort_merge, num_returns=2)
    if n == 1:
        pair = merge.remote(key, descending, *refs)
        return [pair[0]], [api.get(pair[1], timeout=600.0)]
    ordered = sorted(samples)
    boundaries = [ordered[len(ordered) * j // n]
                  for j in builtins.range(1, n)]
    part = _remote(f"sortpart/{n}", _sort_partition, num_returns=n)
    parts = [part.remote(b, key, boundaries, descending) for b in refs]
    out_refs, metas = [], []
    order = builtins.range(n - 1, -1, -1) if descending \
        else builtins.range(n)
    for j in order:
        pair = merge.remote(key, descending, *[p[j] for p in parts])
        out_refs.append(pair[0])
        metas.append(pair[1])
    return out_refs, api.get(metas, timeout=600.0)



def _batches_from_blocks(blocks: Iterator[Block], batch_size: int,
                         batch_format: str,
                         drop_last: bool) -> Iterator[Any]:
    """ONE batching loop for Dataset.iter_batches and DataIterator:
    stream fixed-size batches across block boundaries with a carry."""
    carry: Optional[Block] = None
    for block in blocks:
        if carry is not None:
            block = BlockAccessor.combine([carry, block])
            carry = None
        acc = BlockAccessor(block)
        rows = acc.num_rows()
        start = 0
        while rows - start >= batch_size:
            piece = BlockAccessor(acc.slice(start, start + batch_size))
            yield piece.to_batch(batch_format)
            start += batch_size
        if start < rows:
            carry = acc.slice(start, rows)
    if carry is not None and not drop_last:
        yield BlockAccessor(carry).to_batch(batch_format)


def _torch_convert(batch: Any, dtypes, device) -> Any:
    """numpy batch -> torch tensors with optional dtype/device moves —
    shared by Dataset.iter_torch_batches and DataIterator."""
    import torch

    def _tensor(arr, column=None):
        t = torch.as_tensor(np.ascontiguousarray(arr))
        if isinstance(dtypes, dict):
            if column in dtypes:
                t = t.to(dtypes[column])
        elif dtypes is not None:
            t = t.to(dtypes)
        if device is not None:
            t = t.to(device)
        return t

    if isinstance(batch, dict):
        return {k: _tensor(v, k) for k, v in batch.items()}
    return _tensor(batch)


class Dataset:
    """Distributed rows in object-store blocks, built lazily.

    Transforms record stages on an :class:`ExecutionPlan` (reference:
    `data/_internal/plan.py:74`); nothing runs until a consumption op
    touches ``_blocks``.  Chained map-family stages fuse into one task
    per block.
    """

    def __init__(self, block_refs: List[Any],
                 metadata: Optional[List[BlockMetadata]] = None):
        from .plan import ExecutionPlan
        self._plan = ExecutionPlan.from_blocks(list(block_refs), metadata)

    @classmethod
    def from_plan(cls, plan) -> "Dataset":
        ds = cls.__new__(cls)
        ds._plan = plan
        return ds

    # _blocks/_meta force execution; everything downstream (iteration,
    # splitting, writes, groupby) reads through these two properties.
    @property
    def _blocks(self) -> List[Any]:
        return self._plan.execute()[0]

    @property
    def _meta(self) -> List[BlockMetadata]:
        return self._plan.execute()[1]

    # -- introspection ------------------------------------------------------
    def num_blocks(self) -> int:
        if self._plan.executed:
            return len(self._blocks)
        return self._plan.expected_num_blocks()

    def _ensure_meta(self) -> List[BlockMetadata]:
        refs, meta = self._plan.execute()
        if any(m.num_rows is None for m in meta):
            f = _remote("get_meta", _get_meta)
            meta = api.get([f.remote(b) for b in refs], timeout=300.0)
            self._plan._out = (refs, meta)
        return meta

    def count(self) -> int:
        return sum(m.num_rows for m in self._ensure_meta())

    def size_bytes(self) -> int:
        return sum(m.size_bytes or 0 for m in self._ensure_meta())

    def schema(self):
        meta = self._ensure_meta()
        return meta[0].schema if meta else None

    def input_files(self) -> List[str]:
        out: List[str] = []
        for m in self._ensure_meta():
            out.extend(m.input_files or [])
        return out

    # -- transforms (lazy: each appends a fusable one-to-one stage) ---------
    def _map_all(self, block_fn: Callable[[Block], Block],
                 name: str = "map") -> "Dataset":
        from .plan import OneToOneStage
        return Dataset.from_plan(
            self._plan.with_stage(OneToOneStage(name, block_fn)))

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: str = "native",
                    compute: Any = None) -> "Dataset":
        """``compute=ActorPoolStrategy(size=n)`` (reference:
        `Dataset.map_batches(compute=...)`) runs batches on a pool of
        long-lived actors instead of one task per block — the stateful
        path: ``fn`` may be a CLASS, instantiated once per actor (load
        a model once, map many blocks)."""
        if compute is not None and not (isinstance(compute, str)
                                        and compute == "tasks"):
            return self._map_batches_actors(fn, batch_size,
                                            batch_format, compute)
        if isinstance(fn, type):
            raise ValueError(
                "a callable CLASS needs the actor compute strategy "
                "(pass compute=ActorPoolStrategy(...)): tasks would "
                "re-instantiate it per block")

        def block_fn(block: Block) -> Block:
            return _map_block_batches(fn, block, batch_size,
                                      batch_format)
        return self._map_all(block_fn, "map_batches")

    def _map_batches_actors(self, fn, batch_size, batch_format,
                            compute) -> "Dataset":
        """Executes eagerly: the pool's lifetime brackets the map."""
        if isinstance(compute, ActorPoolStrategy):
            size = compute.size
        elif isinstance(compute, int) and not isinstance(compute, bool) \
                and compute >= 1:
            size = compute
        else:
            raise ValueError(
                f"compute must be \"tasks\", an int pool size >= 1, or "
                f"ActorPoolStrategy(size=n) (got {compute!r})")
        from ..core.serialization import dumps_function
        worker_cls = api.remote(_BatchMapWorker)
        blob = dumps_function(fn)
        actors = [worker_cls.remote(blob)
                  for _ in builtins.range(max(1, size))]
        try:
            pairs = [actors[i % len(actors)].map_block.options(
                num_returns=2).remote(b, batch_size, batch_format)
                for i, b in enumerate(self._blocks)]
            refs = [p[0] for p in pairs]
            # no timeout: stateful maps (model inference over many
            # blocks) legitimately run long; failures surface through
            # the actor-death path, not a wall-clock guess
            metas = api.get([p[1] for p in pairs], timeout=None)
            return Dataset(refs, metas)
        finally:
            for a in actors:
                try:
                    api.kill(a, no_restart=True)
                except Exception:
                    pass

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        def block_fn(block: Block) -> Block:
            return [fn(r) for r in BlockAccessor(block).iter_rows()]
        return self._map_all(block_fn, "map")

    def flat_map(self, fn: Callable[[Any], List[Any]]) -> "Dataset":
        def block_fn(block: Block) -> Block:
            out: List[Any] = []
            for r in BlockAccessor(block).iter_rows():
                out.extend(fn(r))
            return out
        return self._map_all(block_fn, "flat_map")

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        def block_fn(block: Block) -> Block:
            acc = BlockAccessor(block)
            keep = [i for i, r in enumerate(acc.iter_rows()) if fn(r)]
            return acc.take(keep)
        return self._map_all(block_fn, "filter")

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def block_fn(block: Block) -> Block:
            df = BlockAccessor(block).to_pandas().copy()
            df[name] = fn(df)
            return df
        return self._map_all(block_fn, "add_column")

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(lambda df: df.drop(columns=list(cols)),
                                batch_format="pandas")

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(lambda df: df[list(cols)],
                                batch_format="pandas")

    # -- all-to-all (lazy barrier stages) -----------------------------------
    def _two_stage(self, n_out: int, how: str, seed: Optional[int],
                   name: str) -> "Dataset":
        from .plan import AllToAllStage
        return Dataset.from_plan(self._plan.with_stage(AllToAllStage(
            name, lambda refs, meta: _exec_two_stage(refs, n_out, how, seed),
            num_out=n_out)))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._two_stage(num_blocks, "even", None, "repartition")

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        return self._two_stage(num_blocks or max(self.num_blocks(), 1),
                               "shuffle", seed if seed is not None else 0,
                               "random_shuffle")

    def sort(self, key: Optional[str] = None,
             descending: bool = False) -> "Dataset":
        from .plan import AllToAllStage
        return Dataset.from_plan(self._plan.with_stage(AllToAllStage(
            "sort", lambda refs, meta: _exec_sort(refs, meta, key,
                                                  descending))))

    # -- combining ----------------------------------------------------------
    def union(self, *others: "Dataset") -> "Dataset":
        refs = list(self._blocks)
        meta = list(self._meta)
        for o in others:
            refs.extend(o._blocks)
            meta.extend(o._meta)
        return Dataset(refs, meta)

    def zip(self, other: "Dataset") -> "Dataset":
        left = self.to_pandas()
        right = other.to_pandas()
        right.columns = [f"{c}_1" if c in left.columns else c
                         for c in right.columns]
        import pandas as pd
        merged = pd.concat([left.reset_index(drop=True),
                            right.reset_index(drop=True)], axis=1)
        return Dataset([api.put(merged)],
                       [BlockAccessor(merged).metadata()])

    # -- splitting ----------------------------------------------------------
    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        if equal or self.num_blocks() < n:
            ds = self.repartition(n)
            return [Dataset([b], [m]) for b, m in
                    zip(ds._blocks, ds._meta)]
        out = []
        for i in builtins.range(n):
            out.append(Dataset(self._blocks[i::n], self._meta[i::n]))
        return out

    def streaming_split(self, n: int, *,
                        equal: bool = False) -> List["DataIterator"]:
        """N iterators that CONCURRENT consumers (e.g. Train workers)
        drain together, each block consumed exactly once (reference:
        `Dataset.streaming_split` — the coordinated ingest path).
        Unlike `split`, assignment is dynamic: a slow consumer takes
        fewer blocks instead of stalling the epoch.  With ``equal`` the
        dataset repartitions to one block per iterator first."""
        if equal:
            # STATIC assignment: SPMD consumers (train workers doing
            # collectives) need identical batch counts, so each
            # iterator owns exactly one equal block — no coordinator,
            # nothing to leak
            ds = self.repartition(n)
            blocks, meta = ds._blocks, ds._meta
            return [DataIterator(blocks, meta, None, static_indices=[i])
                    for i in builtins.range(n)]
        blocks, meta = self._blocks, self._meta
        # one coordinator actor per split, reclaimed with the job (it
        # is not detached); epochs reuse it instead of re-splitting
        coord = api.remote(_SplitCoordinator).options(
            num_cpus=0.01).remote(len(blocks))
        return [DataIterator(blocks, meta, coord)
                for _ in builtins.range(n)]

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        # Slice in the blocks' NATIVE representation — coercing through
        # pandas would silently turn list-block scalar rows into
        # {"value": ...} dict rows.  Mixed-format datasets (e.g. a union
        # of list and dataframe blocks) fall back to the pandas path,
        # which combine() cannot represent natively.
        blocks = api.get(list(self._blocks), timeout=300.0) \
            if self._blocks else []
        kinds = {type(b) for b in blocks}
        if len(kinds) > 1:
            combined = self.to_pandas()
        elif blocks:
            combined = BlockAccessor.combine(blocks)
        else:
            combined = []
        acc = BlockAccessor(combined)
        out, prev = [], 0
        for idx in list(indices) + [acc.num_rows()]:
            piece = acc.slice(prev, idx)
            out.append(Dataset([api.put(piece)],
                               [BlockAccessor(piece).metadata()]))
            prev = idx
        return out

    def split_proportionately(self, proportions: List[float]
                              ) -> List["Dataset"]:
        """Split by fractions; the remainder becomes the final split
        (reference: `Dataset.split_proportionately` — len(proportions)
        + 1 datasets)."""
        if not proportions or any(p <= 0 for p in proportions) \
                or sum(proportions) >= 1.0:
            raise ValueError("proportions must be positive and sum to "
                             "< 1 (the remainder is the last split)")
        n = self.count()
        indices, acc = [], 0.0
        for p in proportions:
            acc += p
            # round, not truncate: int(50*0.58) is 28 from float error
            indices.append(builtins.round(n * acc))
        return self.split_at_indices(indices)

    def train_test_split(self, test_size: float, *,
                         shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> List["Dataset"]:
        """(train, test) by fraction (reference:
        `Dataset.train_test_split`)."""
        if not 0.0 < test_size < 1.0:
            raise ValueError("test_size must be in (0, 1)")
        ds = self.random_shuffle(seed=seed) if shuffle else self
        return ds.split_proportionately([1.0 - test_size])

    def random_sample(self, fraction: float, *,
                      seed: Optional[int] = None) -> "Dataset":
        """Row-level Bernoulli sample (reference:
        `Dataset.random_sample`)."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError("fraction must be in [0, 1]")
        sample = _remote("random_sample_block",
                 _bernoulli_sample_block)
        # executes eagerly (the per-block index needs the block list);
        # downstream stages are lazy again on the result
        return Dataset([sample.remote(b, i, seed, fraction)
                        for i, b in enumerate(self._blocks)])

    def randomize_block_order(self, *, seed: Optional[int] = None
                              ) -> "Dataset":
        """Shuffle BLOCK order only — the cheap epoch-to-epoch
        decorrelation (reference: `Dataset.randomize_block_order`)."""
        import numpy as _np
        rng = _np.random.default_rng(seed)
        order = rng.permutation(self.num_blocks()).tolist()
        return Dataset([self._blocks[i] for i in order],
                       [self._meta[i] for i in order])

    def aggregate(self, *aggs) -> Any:
        """Whole-dataset aggregation with the GroupedData agg tuples
        (reference: `Dataset.aggregate`): ``aggregate(("mean", "x"),
        ("max", "x"))`` → dict of results.  ONE column pull per unique
        column, however many aggregations read it."""
        known = {"count": len, "sum": np.sum, "min": np.min,
                 "max": np.max, "mean": np.mean, "std": np.std}
        for name, _ in aggs:
            if name not in known:
                raise ValueError(f"unknown aggregation {name!r} "
                                 f"(supported: {sorted(known)})")
        values = {c: self._column_values(c)
                  for c in {col for _, col in aggs}}
        return {f"{name}({col})": float(known[name](values[col]))
                for name, col in aggs}

    def copy(self) -> "Dataset":
        """New handle sharing this dataset's plan — lazy stages stay
        lazy; execution results are shared (blocks are immutable)."""
        return Dataset.from_plan(self._plan)

    # -- reference-name aliases (the execution model is already lazy) --
    def lazy(self) -> "Dataset":
        return self

    def fully_executed(self) -> "Dataset":
        return self.materialize()

    def is_fully_executed(self) -> bool:
        return self._plan.executed

    def get_internal_block_refs(self) -> List[Any]:
        """The block ObjectRefs (reference:
        `Dataset.get_internal_block_refs`)."""
        return list(self._blocks)

    def to_pandas_refs(self) -> List[Any]:
        """One DataFrame ref per block (reference:
        `Dataset.to_pandas_refs` — zero driver materialization)."""
        @api.remote
        def _to_df(block: Block):
            return BlockAccessor(block).to_pandas()
        return [_to_df.remote(b) for b in self._blocks]

    def to_numpy_refs(self, column: Optional[str] = None) -> List[Any]:
        """One ndarray ref per block (reference:
        `Dataset.to_numpy_refs`)."""
        @api.remote
        def _to_np(block: Block, _col=column):
            df = BlockAccessor(block).to_pandas()
            return df[_col].to_numpy() if _col else df.to_numpy()
        return [_to_np.remote(b) for b in self._blocks]

    def to_torch(self, *, batch_size: int = 256,
                 dtypes: Any = None, device: Any = None):
        """Torch IterableDataset over this dataset (reference:
        `Dataset.to_torch`)."""
        import torch
        outer = self

        class _IterableDataset(torch.utils.data.IterableDataset):
            def __iter__(self):
                return outer.iter_torch_batches(
                    batch_size=batch_size, dtypes=dtypes,
                    device=device)
        return _IterableDataset()

    def iter_tf_batches(self, **kwargs):
        """TensorFlow is not in this image; the reference capability is
        gated with a clear error (cf. runtime_env conda gating)."""
        raise ImportError(
            "iter_tf_batches/to_tf need tensorflow, which this image "
            "does not ship; use iter_batches (numpy) or "
            "iter_torch_batches")

    to_tf = iter_tf_batches

    def write_numpy(self, path: str, *,
                    column: Optional[str] = None) -> None:
        """One .npy file per block (reference:
        `Dataset.write_numpy`).  Blocks fetch ONE at a time — peak
        driver memory is a single block, not the dataset.  Without
        ``column`` the whole block writes as a STRUCTURED array
        (to_records), so `read_numpy` restores column names/dtypes."""
        os.makedirs(path, exist_ok=True)
        if column is None:
            # the datasource path fans out one write task per block —
            # no driver materialization, and ONE definition of the
            # structured-records format (NumpyDatasource._write_file)
            from .datasource import NumpyDatasource
            self.write_datasource(NumpyDatasource(), path=path)
            return
        for i, ref in enumerate(self.to_numpy_refs(column=column)):
            arr = api.get(ref, timeout=600.0)
            np.save(os.path.join(path, f"block_{i:05d}.npy"), arr)

    def limit(self, n: int) -> "Dataset":
        taken: List[Block] = []
        total = 0
        for ref, meta in zip(self._blocks, self._ensure_meta()):
            if total >= n:
                break
            block = api.get(ref, timeout=300.0)
            acc = BlockAccessor(block)
            take = min(acc.num_rows(), n - total)
            taken.append(acc.slice(0, take))
            total += take
        return Dataset([api.put(b) for b in taken],
                       [BlockAccessor(b).metadata() for b in taken])

    # -- consumption --------------------------------------------------------
    def iter_rows(self) -> Iterator[Any]:
        for ref in self._blocks:
            yield from BlockAccessor(api.get(ref, timeout=300.0)).iter_rows()

    def take(self, n: int = 20) -> List[Any]:
        return list(itertools.islice(self.iter_rows(), n))

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Any]:
        """Stream batches across block boundaries (Train ingest path)."""
        blocks = (api.get(ref, timeout=300.0) for ref in self._blocks)
        yield from _batches_from_blocks(blocks, batch_size, batch_format,
                                        drop_last)

    def iter_torch_batches(self, *, batch_size: int = 256,
                           dtypes=None, device: Optional[str] = None,
                           drop_last: bool = False) -> Iterator[Any]:
        """iter_batches with torch-tensor conversion (reference:
        `Dataset.iter_torch_batches` — the Torch ingest path).  Columnar
        batches become {column: tensor}; array batches become one
        tensor."""
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            yield _torch_convert(batch, dtypes, device)

    def to_pandas(self):
        blocks = [BlockAccessor(api.get(r, timeout=300.0)).to_pandas()
                  for r in self._blocks]
        import pandas as pd
        return pd.concat(blocks, ignore_index=True) if blocks \
            else pd.DataFrame()

    def to_numpy(self, column: Optional[str] = None):
        chunks = [BlockAccessor(api.get(r, timeout=300.0)).to_numpy(column)
                  for r in self._blocks]
        if not chunks:
            return np.asarray([])
        if isinstance(chunks[0], dict):
            return {k: np.concatenate([c[k] for c in chunks])
                    for k in chunks[0]}
        return np.concatenate(chunks)

    def materialize(self) -> "Dataset":
        self._ensure_meta()
        return self

    # -- aggregates ---------------------------------------------------------
    def _column_values(self, column: Optional[str]) -> np.ndarray:
        vals: List[Any] = []
        for r in self.iter_rows():
            vals.append(r[column] if column else r)
        return np.asarray(vals)

    def sum(self, column: Optional[str] = None):
        return self._column_values(column).sum()

    def min(self, column: Optional[str] = None):
        return self._column_values(column).min()

    def max(self, column: Optional[str] = None):
        return self._column_values(column).max()

    def mean(self, column: Optional[str] = None):
        return float(self._column_values(column).mean())

    def std(self, column: Optional[str] = None):
        return float(self._column_values(column).std(ddof=1))

    def groupby(self, key: str):
        from .grouped import GroupedData
        return GroupedData(self, key)

    # -- IO (through the Datasource ABC; reference:
    # `data/datasource/datasource.py:1` do_write) ---------------------------
    def write_datasource(self, datasource, *, path: str,
                         **write_args) -> List[Any]:
        import os
        os.makedirs(path, exist_ok=True)
        blocks = self._blocks  # plan execution errors are not write errors
        try:
            return datasource.do_write(blocks, path, **write_args)
        except Exception as exc:
            datasource.on_write_failed(exc)
            raise

    def write_parquet(self, path: str, **kw) -> List[str]:
        from .datasource import ParquetDatasource
        return self.write_datasource(ParquetDatasource(), path=path, **kw)

    def write_csv(self, path: str, **kw) -> List[str]:
        from .datasource import CSVDatasource
        return self.write_datasource(CSVDatasource(), path=path, **kw)

    def write_json(self, path: str, **kw) -> List[str]:
        from .datasource import JSONDatasource
        return self.write_datasource(JSONDatasource(), path=path, **kw)

    def write_tfrecords(self, path: str, **kw) -> List[str]:
        from .tfrecords import TFRecordDatasource
        return self.write_datasource(TFRecordDatasource(), path=path,
                                     **kw)

    # -- pipeline -----------------------------------------------------------
    def window(self, *, blocks_per_window: int = 10):
        from .dataset_pipeline import DatasetPipeline
        return DatasetPipeline.from_windows(
            [Dataset(self._blocks[i:i + blocks_per_window],
                     self._meta[i:i + blocks_per_window])
             for i in builtins.range(0, len(self._blocks),
                                     blocks_per_window)])

    def repeat(self, times: int):
        from .dataset_pipeline import DatasetPipeline
        return DatasetPipeline.from_windows([self] * times)

    def stats(self) -> str:
        """Per-stage execution report + dataset summary (reference:
        `data/_internal/stats.py:1`).  Forces execution."""
        meta = self._ensure_meta()
        lines = [s.line(i) for i, s in enumerate(self._plan.stats())]
        lines.append(f"Dataset(blocks={len(meta)}, "
                     f"rows={sum(m.num_rows or 0 for m in meta)}, "
                     f"bytes={sum(m.size_bytes or 0 for m in meta)})")
        return "\n".join(lines)

    def __repr__(self):
        if not self._plan.executed:
            return (f"Dataset(num_blocks={self.num_blocks()}, "
                    f"lazy stages={self._plan.stage_names()})")
        return (f"Dataset(num_blocks={self.num_blocks()}, "
                f"num_rows={self._meta[0].num_rows and self.count()})")


class _SplitCoordinator:
    """Actor handing out block indices to streaming-split consumers —
    each index exactly once PER EPOCH, dynamically (reference: the
    streaming split coordinator in _internal/execution).  An epoch is
    one full pass; each call of DataIterator.iter_batches opens the
    consumer's next epoch, so standard multi-epoch training loops work
    without explicit resets."""

    def __init__(self, n_blocks: int):
        self._n = n_blocks
        self._pos: Dict[int, int] = {}   # epoch -> next unassigned index

    def next_block_index(self, epoch: int) -> Optional[int]:
        i = self._pos.get(epoch, 0)
        if i >= self._n:
            return None
        self._pos[epoch] = i + 1
        # old epochs never get new requests once every consumer moved on;
        # drop them so the dict stays bounded
        for e in [e for e in self._pos if e < epoch - 2]:
            del self._pos[e]
        return i


class DataIterator:
    """One streaming-split consumer's view (reference: DataIterator).
    Picklable — block refs and the coordinator handle ship to worker
    actors.  Dynamic mode pulls coordinator-assigned blocks (a slow
    consumer takes fewer); ``equal`` mode iterates a fixed block
    subset so every SPMD consumer sees the same batch count.  Each
    ``iter_batches`` call is one epoch; iterating again replays the
    dataset."""

    def __init__(self, blocks: List[Any], meta: List[BlockMetadata],
                 coord: Optional[Any],
                 static_indices: Optional[List[int]] = None):
        self._block_refs = list(blocks)
        self._meta = list(meta)
        self._coord = coord
        self._static = static_indices
        self._epoch = 0

    def _assigned_blocks(self) -> Iterator[Block]:
        if self._static is not None:
            for i in self._static:
                yield api.get(self._block_refs[i], timeout=300.0)
            return
        epoch = self._epoch
        while True:
            idx = api.get(self._coord.next_block_index.remote(epoch),
                          timeout=300.0)
            if idx is None:
                return
            yield api.get(self._block_refs[idx], timeout=300.0)

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Any]:
        try:
            yield from _batches_from_blocks(
                self._assigned_blocks(), batch_size, batch_format,
                drop_last)
        finally:
            self._epoch += 1

    def iter_torch_batches(self, *, batch_size: int = 256, dtypes=None,
                           device: Optional[str] = None,
                           drop_last: bool = False) -> Iterator[Any]:
        for batch in self.iter_batches(batch_size=batch_size,
                                       drop_last=drop_last):
            yield _torch_convert(batch, dtypes, device)
