"""TFRecord datasource: the TPU ecosystem's native file format.

Capability mirror of the reference's TFRecords datasource
(/root/reference/python/ray/data/datasource/tfrecords_datasource.py —
`tf.train.Example` records in the length-prefixed, CRC-masked TFRecord
container).  This image ships no TensorFlow, so BOTH layers are
implemented directly:

  * the TFRecord container — ``uint64 length | masked crc32c(length) |
    data | masked crc32c(data)`` with the Castagnoli polynomial and
    TensorFlow's mask rotation; and
  * the `tf.train.Example` protobuf wire format — a hand-rolled codec
    for the fixed three-level schema (Example → Features →
    map<string, Feature{bytes_list|float_list|int64_list}>), which is
    stable and tiny enough that a dependency would be heavier than the
    codec.

Files written here are readable by real TensorFlow/`tf.data`, and vice
versa — the point of the format on TPU pipelines.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List

from .datasource import FileBasedDatasource

# -- crc32c (Castagnoli), table-driven -------------------------------------

_CRC_TABLE: List[int] = []


def _crc_table() -> List[int]:
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


try:                               # a C implementation when one exists
    from crc32c import crc32c as _crc32c_fast       # pragma: no cover
except ImportError:
    try:
        from google_crc32c import value as _crc32c_fast  # pragma: no cover
    except ImportError:
        _crc32c_fast = None


def crc32c(data: bytes) -> int:
    if _crc32c_fast is not None:                    # pragma: no cover
        return _crc32c_fast(data)
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# -- protobuf wire helpers --------------------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _tag(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


# -- tf.train.Example codec --------------------------------------------------


def encode_example(row: Dict[str, Any]) -> bytes:
    """Column dict → serialized `tf.train.Example`.  Value mapping
    follows the reference datasource: bytes/str → bytes_list, floats →
    float_list, ints/bools → int64_list; lists/arrays of those map to
    multi-value features."""
    import numpy as np
    features = b""
    for key, value in row.items():
        if isinstance(value, np.ndarray):
            value = value.tolist()
        if not isinstance(value, (list, tuple)):
            value = [value]
        # type is decided over the WHOLE list: any float anywhere makes
        # it a float_list (sniffing only value[0] would silently
        # truncate [1, 2.5] to ints)
        if any(isinstance(v, (bytes, str)) for v in value):
            if not all(isinstance(v, (bytes, str)) for v in value):
                raise TypeError(
                    f"feature {key!r} mixes bytes/str with numbers: "
                    f"{value!r}")
            payload = b"".join(
                _len_delim(1, v.encode() if isinstance(v, str) else v)
                for v in value)
            feature = _len_delim(1, payload)              # bytes_list
        elif any(isinstance(v, (float, np.floating)) for v in value):
            packed = struct.pack(f"<{len(value)}f",
                                 *[float(v) for v in value])
            feature = _len_delim(2, _len_delim(1, packed))  # float_list
        else:
            packed = b"".join(_varint(int(v) & 0xFFFFFFFFFFFFFFFF)
                              for v in value)
            feature = _len_delim(3, _len_delim(1, packed))  # int64_list
        entry = _len_delim(1, key.encode()) + _len_delim(2, feature)
        features += _len_delim(1, entry)                  # map entry
    return _len_delim(1, features)                        # Example.features


def _parse_fields(buf: bytes) -> Iterator:
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 2:
            ln, pos = _read_varint(buf, pos)
            yield field, buf[pos:pos + ln]
            pos += ln
        elif wire == 0:
            v, pos = _read_varint(buf, pos)
            yield field, v
        elif wire == 5:
            yield field, buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            yield field, buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")


def decode_example(data: bytes) -> Dict[str, Any]:
    """Serialized `tf.train.Example` → column dict.  Single-element
    features unwrap to scalars (the reference's behavior)."""
    row: Dict[str, Any] = {}
    for f_ex, features in _parse_fields(data):
        if f_ex != 1:
            continue
        for f_map, entry in _parse_fields(features):
            key = None
            value: Any = None
            for f_e, v in _parse_fields(entry):
                if f_e == 1:
                    key = v.decode()
                elif f_e == 2:
                    value = _decode_feature(v)
            if key is not None:
                row[key] = value
    return row


def _decode_feature(buf: bytes):
    for kind, payload in _parse_fields(buf):
        if kind == 1:       # bytes_list
            vals = [v for f, v in _parse_fields(payload) if f == 1]
            return vals[0] if len(vals) == 1 else vals
        if kind == 2:       # float_list (packed or repeated)
            floats: List[float] = []
            for f, v in _parse_fields(payload):
                if f == 1:
                    if isinstance(v, bytes):
                        floats.extend(struct.unpack(
                            f"<{len(v) // 4}f", v))
                    else:   # unpacked fixed32 comes as 4 bytes too
                        floats.append(float(v))
            return floats[0] if len(floats) == 1 else floats
        if kind == 3:       # int64_list (packed varints)
            ints: List[int] = []
            for f, v in _parse_fields(payload):
                if f == 1:
                    if isinstance(v, bytes):
                        pos = 0
                        while pos < len(v):
                            n, pos = _read_varint(v, pos)
                            # two's-complement back to signed
                            if n >= 1 << 63:
                                n -= 1 << 64
                            ints.append(n)
                    else:
                        ints.append(v if v < 1 << 63 else v - (1 << 64))
            return ints[0] if len(ints) == 1 else ints
    return None


# -- the container + datasource ---------------------------------------------


def write_tfrecord_file(path: str, records: List[bytes]) -> None:
    with open(path, "wb") as f:
        for rec in records:
            length = struct.pack("<Q", len(rec))
            f.write(length)
            f.write(struct.pack("<I", _masked_crc(length)))
            f.write(rec)
            f.write(struct.pack("<I", _masked_crc(rec)))


def read_tfrecord_file(path: str,
                       verify_crc: bool = True) -> Iterator[bytes]:
    """``verify_crc=False`` skips checksum verification (the tf.data
    reader's own default) — with the pure-Python CRC fallback that is
    the dominant cost of reading large files."""
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return
            if len(header) < 8:
                raise ValueError(f"truncated TFRecord header in {path}")
            (length,) = struct.unpack("<Q", header)
            crc_buf = f.read(4)
            data = f.read(length)
            crc_buf2 = f.read(4)
            if len(crc_buf) < 4 or len(data) < length \
                    or len(crc_buf2) < 4:
                raise ValueError(f"truncated TFRecord record in {path}")
            if verify_crc:
                if struct.unpack("<I", crc_buf)[0] != \
                        _masked_crc(header):
                    raise ValueError(
                        f"corrupt TFRecord length crc in {path}")
                if struct.unpack("<I", crc_buf2)[0] != \
                        _masked_crc(data):
                    raise ValueError(
                        f"corrupt TFRecord data crc in {path}")
            yield data


class TFRecordDatasource(FileBasedDatasource):
    """`tf.train.Example` TFRecord files ⇄ tabular blocks."""

    _FILE_EXT = "tfrecords"

    def _read_file(self, path: str, verify_crc: bool = True, **kw):
        import pandas as pd
        rows = [decode_example(rec)
                for rec in read_tfrecord_file(path,
                                              verify_crc=verify_crc)]
        return pd.DataFrame(rows)

    def _write_file(self, df, path: str, **kw) -> None:
        write_tfrecord_file(
            path, [encode_example(row)
                   for row in df.to_dict(orient="records")])
