"""Preprocessor core: fit on a Dataset, transform Datasets and batches.

Capability mirror of the reference's AIR preprocessor layer
(/root/reference/python/ray/data/preprocessor.py:21 — Preprocessor with
fit/transform/transform_batch and a fit-state contract;
preprocessors/chain.py:8; preprocessors/batch_mapper.py:12).  Design
differences: fit statistics are computed as one small partial dict per
block gathered through the existing lazy plan machinery (map_batches →
take_all) instead of the reference's Dataset.aggregate GroupBy path, and
the fitted state is plain picklable attributes so a preprocessor rides a
Checkpoint (``Checkpoint.with_preprocessor``) into BatchPredictor/Serve.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


class PreprocessorNotFittedError(RuntimeError):
    """transform called before fit on a fittable preprocessor."""


class Preprocessor:
    """Fit state from a Dataset; row-preserving transforms of batches.

    Subclasses implement ``_fit(dataset)`` (set ``self.stats_``; skip if
    stateless — set ``_is_fittable = False``) and
    ``_transform_pandas(df) -> df``.
    """

    _is_fittable = True

    # -- fitting ------------------------------------------------------------
    def fit(self, dataset: Any) -> "Preprocessor":
        if self._is_fittable:
            self._fit(dataset)
        return self

    def fit_transform(self, dataset: Any) -> Any:
        return self.fit(dataset).transform(dataset)

    def _fit(self, dataset: Any) -> None:
        raise NotImplementedError

    @property
    def fitted(self) -> bool:
        return not self._is_fittable or \
            getattr(self, "stats_", None) is not None

    def _check_fitted(self) -> None:
        if not self.fitted:
            raise PreprocessorNotFittedError(
                f"{type(self).__name__} must be fit before transforming")

    # -- transforming -------------------------------------------------------
    def transform(self, dataset: Any) -> Any:
        self._check_fitted()
        return dataset.map_batches(self._transform_pandas,
                                   batch_format="pandas")

    def transform_batch(self, batch: Any) -> Any:
        """Batch (DataFrame | dict-of-arrays | list-of-dicts) → same
        format, transformed.  The online-inference entry point
        (BatchPredictor / Serve replicas)."""
        self._check_fitted()
        df, restore = _to_pandas(batch)
        return restore(self._transform_pandas(df))

    def _transform_pandas(self, df):
        raise NotImplementedError

    def __repr__(self):
        state = "fitted" if self.fitted else "not fitted"
        return f"{type(self).__name__}({state})"


# -- batch format round trip -------------------------------------------------

def _to_pandas(batch: Any):
    """→ (DataFrame, restore_fn) where restore_fn returns the caller's
    original batch format."""
    import pandas as pd
    if isinstance(batch, pd.DataFrame):
        return batch, lambda df: df
    if isinstance(batch, dict):
        return pd.DataFrame({k: list(v) if getattr(v, "ndim", 1) > 1
                             else v for k, v in batch.items()}), \
            lambda df: {c: np.asarray(list(df[c])) for c in df.columns}
    if isinstance(batch, list):
        return pd.DataFrame(batch), \
            lambda df: df.to_dict(orient="records")
    if isinstance(batch, np.ndarray):
        cols = [f"f{i}" for i in range(batch.shape[-1])] \
            if batch.ndim == 2 else ["f0"]
        return pd.DataFrame(np.atleast_2d(batch), columns=cols), \
            lambda df: df.to_numpy()
    raise TypeError(f"unsupported batch type {type(batch)}")


# -- distributed fit plumbing -------------------------------------------------

def block_partials(dataset: Any,
                   partial_fn: Callable[[Any], Dict[str, Any]]
                   ) -> List[Dict[str, Any]]:
    """One small stats dict per block, computed where the block lives
    and gathered to the driver — the fit-side scan every fittable
    preprocessor shares."""
    parts = dataset.map_batches(lambda df: [partial_fn(df)],
                                batch_format="pandas")
    return [p for p in parts.take_all() if p is not None]


def numeric_column(df, col: str) -> np.ndarray:
    """Column as float ndarray with NaNs preserved (fit-side helper)."""
    return df[col].to_numpy(dtype=np.float64, na_value=np.nan)


# -- stateless wrappers -------------------------------------------------------

class BatchMapper(Preprocessor):
    """User function over batches (reference:
    preprocessors/batch_mapper.py:12) — the escape hatch that makes any
    row-preserving transform composable in a Chain."""

    _is_fittable = False

    def __init__(self, fn: Callable[[Any], Any],
                 batch_format: str = "pandas"):
        self.fn = fn
        self.batch_format = batch_format

    def _transform_pandas(self, df):
        if self.batch_format == "pandas":
            return self.fn(df)
        df2, restore = _to_pandas(
            self.fn({c: df[c].to_numpy() for c in df.columns}))
        return df2


class Chain(Preprocessor):
    """Sequential composition (reference: preprocessors/chain.py:8).

    ``fit`` is staged: each preprocessor fits on the output of its
    predecessors (the transforms stay lazy plan stages, so the chain
    fit is still one pass per fittable stage, not a materialization).
    """

    def __init__(self, *preprocessors: Preprocessor):
        self.preprocessors = list(preprocessors)

    @property
    def _is_fittable(self):  # type: ignore[override]
        return any(p._is_fittable for p in self.preprocessors)

    @property
    def fitted(self) -> bool:
        return all(p.fitted for p in self.preprocessors)

    def _fit(self, dataset: Any) -> None:
        for p in self.preprocessors:
            dataset = p.fit(dataset).transform(dataset)

    def fit_transform(self, dataset: Any) -> Any:
        for p in self.preprocessors:
            dataset = p.fit(dataset).transform(dataset)
        return dataset

    def transform(self, dataset: Any) -> Any:
        self._check_fitted()
        for p in self.preprocessors:
            dataset = p.transform(dataset)
        return dataset

    def _transform_pandas(self, df):
        for p in self.preprocessors:
            df = p._transform_pandas(df)
        return df

    def __repr__(self):
        inner = ", ".join(repr(p) for p in self.preprocessors)
        return f"Chain({inner})"
