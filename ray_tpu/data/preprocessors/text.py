"""Text/feature-hashing preprocessors (reference:
/root/reference/python/ray/data/preprocessors/tokenizer.py:9,
hasher.py:9, vectorizer.py:12 — Tokenizer, FeatureHasher,
CountVectorizer, HashingVectorizer).

Hashing uses a keyed stable hash (md5 of the token bytes), NOT Python's
per-process-randomized ``hash`` — transforms must agree across workers.
"""

from __future__ import annotations

import hashlib
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .base import Preprocessor, block_partials


def _stable_hash(token: str, mod: int) -> int:
    digest = hashlib.md5(str(token).encode()).digest()
    return int.from_bytes(digest[:8], "little") % mod


def _default_tokenize(text: str) -> List[str]:
    return str(text).lower().split()


class Tokenizer(Preprocessor):
    """string column → list-of-tokens column.  Stateless."""

    _is_fittable = False

    def __init__(self, columns: List[str],
                 tokenization_fn: Optional[Callable] = None):
        self.columns = list(columns)
        self.tokenization_fn = tokenization_fn or _default_tokenize

    def _transform_pandas(self, df):
        df = df.copy()
        for c in self.columns:
            df[c] = df[c].map(self.tokenization_fn)
        return df


class FeatureHasher(Preprocessor):
    """Rows of {column: count} → fixed-width hashed count vector in
    ``output_column`` (reference: hasher.py — the sparse-to-dense
    bridge for bag-of-words at vocabulary scale).  Stateless."""

    _is_fittable = False

    def __init__(self, columns: List[str], num_features: int,
                 output_column: str = "hashed_features"):
        self.columns = list(columns)
        self.num_features = num_features
        self.output_column = output_column

    def _transform_pandas(self, df):
        df = df.copy()
        mat = np.zeros((len(df), self.num_features), dtype=np.float64)
        for c in self.columns:
            j_by_col = _stable_hash(c, self.num_features)
            mat[:, j_by_col] += df[c].to_numpy(dtype=np.float64)
        out = df.drop(columns=self.columns)
        out[self.output_column] = list(mat)
        return out


class CountVectorizer(Preprocessor):
    """Token-list columns → count vectors over a FITTED vocabulary
    (top ``max_features`` by corpus frequency, ties broken
    alphabetically for determinism)."""

    def __init__(self, columns: List[str],
                 max_features: Optional[int] = None):
        self.columns = list(columns)
        self.max_features = max_features

    def _fit(self, dataset: Any) -> None:
        def partial(df):
            out = {}
            for c in self.columns:
                counts: Dict[str, int] = {}
                for row in df[c].dropna():
                    for tok in row:
                        counts[tok] = counts.get(tok, 0) + 1
                out[c] = counts
            return out
        merged: Dict[str, Dict[str, int]] = {c: {} for c in self.columns}
        for p in block_partials(dataset, partial):
            for c in self.columns:
                for tok, n in p[c].items():
                    merged[c][tok] = merged[c].get(tok, 0) + n
        stats = {}
        for c in self.columns:
            toks = sorted(merged[c].items(), key=lambda kv: (-kv[1],
                                                             kv[0]))
            if self.max_features is not None:
                toks = toks[:self.max_features]
            stats[c] = {tok: i for i, tok in
                        enumerate(sorted(t for t, _ in toks))}
        self.stats_ = stats

    def _transform_pandas(self, df):
        df = df.copy()
        for c in self.columns:
            vocab = self.stats_[c]
            k = len(vocab)

            def encode(row, _vocab=vocab, _k=k):
                vec = np.zeros(_k, dtype=np.int64)
                for tok in (row or ()):
                    i = _vocab.get(tok)
                    if i is not None:
                        vec[i] += 1
                return vec
            df[c] = df[c].map(encode)
        return df


class HashingVectorizer(Preprocessor):
    """Token-list columns → hashed count vectors, no fit (reference:
    vectorizer.py HashingVectorizer).  Stateless."""

    _is_fittable = False

    def __init__(self, columns: List[str], num_features: int):
        self.columns = list(columns)
        self.num_features = num_features

    def _transform_pandas(self, df):
        df = df.copy()
        for c in self.columns:
            def encode(row, _m=self.num_features):
                vec = np.zeros(_m, dtype=np.int64)
                for tok in (row or ()):
                    vec[_stable_hash(tok, _m)] += 1
                return vec
            df[c] = df[c].map(encode)
        return df
