"""Numeric preprocessors: scalers, imputer, normalizer, discretizers,
power transform, concatenator.

Capability mirrors: /root/reference/python/ray/data/preprocessors/
scaler.py:14 (Standard/MinMax/MaxAbs/Robust), imputer.py:12,
normalizer.py:9, discretizer.py (Uniform/CustomKBins), transformer.py:9
(PowerTransformer), concatenator.py:9.  Fit statistics are mergeable
per-block partials (sum/sumsq, min/max, value counts, sorted samples)
combined on the driver — associative merges, so block order never
changes the result (except the documented RobustScaler sampling).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .base import Preprocessor, block_partials, numeric_column

#: per-block cap on values contributed to quantile estimation
#: (RobustScaler, median imputing).  Exact when every block is under the
#: cap; an evenly-strided subsample (not a prefix) beyond it.
_QUANTILE_SAMPLE_CAP = 65536


def _sample_sorted(vals: np.ndarray) -> np.ndarray:
    vals = vals[~np.isnan(vals)]
    if vals.size > _QUANTILE_SAMPLE_CAP:
        idx = np.linspace(0, vals.size - 1, _QUANTILE_SAMPLE_CAP,
                          dtype=np.int64)
        vals = np.sort(vals)[idx]
    return vals


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference: scaler.py:14)."""

    def __init__(self, columns: List[str], ddof: int = 0):
        self.columns = list(columns)
        self.ddof = ddof

    def _fit(self, dataset: Any) -> None:
        def partial(df):
            out = {}
            for c in self.columns:
                v = numeric_column(df, c)
                v = v[~np.isnan(v)]
                out[c] = (v.size, float(v.sum()),
                          float((v ** 2).sum()))
            return out
        stats: Dict[str, Any] = {}
        for c in self.columns:
            n = s = ss = 0.0
            for p in block_partials(dataset, partial):
                pn, ps, pss = p[c]
                n, s, ss = n + pn, s + ps, ss + pss
            mean = s / max(n, 1)
            var = max(ss / max(n, 1) - mean ** 2, 0.0)
            if self.ddof and n > self.ddof:
                var *= n / (n - self.ddof)
            stats[c] = (mean, float(np.sqrt(var)))
        self.stats_ = stats

    def _transform_pandas(self, df):
        df = df.copy()
        for c in self.columns:
            mean, std = self.stats_[c]
            df[c] = (df[c] - mean) / (std if std > 0 else 1.0)
        return df


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column (reference: scaler.py)."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)

    def _fit(self, dataset: Any) -> None:
        def partial(df):
            out = {}
            for c in self.columns:
                v = numeric_column(df, c)
                v = v[~np.isnan(v)]
                out[c] = (float(v.min()) if v.size else np.inf,
                          float(v.max()) if v.size else -np.inf)
            return out
        stats = {}
        for c in self.columns:
            lo, hi = np.inf, -np.inf
            for p in block_partials(dataset, partial):
                lo, hi = min(lo, p[c][0]), max(hi, p[c][1])
            stats[c] = (lo, hi)
        self.stats_ = stats

    def _transform_pandas(self, df):
        df = df.copy()
        for c in self.columns:
            lo, hi = self.stats_[c]
            span = hi - lo
            df[c] = (df[c] - lo) / (span if span > 0 else 1.0)
        return df


class MaxAbsScaler(Preprocessor):
    """x / max|x| per column (reference: scaler.py)."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)

    def _fit(self, dataset: Any) -> None:
        def partial(df):
            out = {}
            for c in self.columns:
                v = numeric_column(df, c)
                v = v[~np.isnan(v)]
                out[c] = float(np.abs(v).max()) if v.size else 0.0
            return out
        stats = {c: 0.0 for c in self.columns}
        for p in block_partials(dataset, partial):
            for c in self.columns:
                stats[c] = max(stats[c], p[c])
        self.stats_ = stats

    def _transform_pandas(self, df):
        df = df.copy()
        for c in self.columns:
            m = self.stats_[c]
            df[c] = df[c] / (m if m > 0 else 1.0)
        return df


class RobustScaler(Preprocessor):
    """(x - median) / IQR (reference: scaler.py RobustScaler).

    Quantiles come from per-block sorted samples merged on the driver —
    exact up to ``_QUANTILE_SAMPLE_CAP`` rows per block, an evenly
    strided subsample beyond it.
    """

    def __init__(self, columns: List[str],
                 quantile_range: Tuple[float, float] = (0.25, 0.75)):
        self.columns = list(columns)
        self.quantile_range = quantile_range

    def _fit(self, dataset: Any) -> None:
        def partial(df):
            return {c: _sample_sorted(numeric_column(df, c))
                    for c in self.columns}
        parts = block_partials(dataset, partial)
        lo_q, hi_q = self.quantile_range
        stats = {}
        for c in self.columns:
            merged = np.concatenate([p[c] for p in parts]) \
                if parts else np.array([0.0])
            med = float(np.quantile(merged, 0.5))
            iqr = float(np.quantile(merged, hi_q)
                        - np.quantile(merged, lo_q))
            stats[c] = (med, iqr)
        self.stats_ = stats

    def _transform_pandas(self, df):
        df = df.copy()
        for c in self.columns:
            med, iqr = self.stats_[c]
            df[c] = (df[c] - med) / (iqr if iqr > 0 else 1.0)
        return df


class SimpleImputer(Preprocessor):
    """Fill missing values (reference: imputer.py:12).  Strategies:
    mean, median (sampled like RobustScaler), most_frequent, constant."""

    def __init__(self, columns: List[str], strategy: str = "mean",
                 fill_value: Any = None):
        if strategy not in ("mean", "median", "most_frequent",
                            "constant"):
            raise ValueError(f"unknown strategy {strategy!r}")
        if strategy == "constant" and fill_value is None:
            raise ValueError("strategy='constant' needs fill_value")
        self.columns = list(columns)
        self.strategy = strategy
        self.fill_value = fill_value

    _is_fittable = property(
        lambda self: self.strategy != "constant")  # type: ignore

    def _fit(self, dataset: Any) -> None:
        strat = self.strategy

        def partial(df):
            out = {}
            for c in self.columns:
                if strat == "most_frequent":
                    vc = df[c].dropna().value_counts()
                    out[c] = dict(vc.iloc[:256])
                elif strat == "median":
                    out[c] = _sample_sorted(numeric_column(df, c))
                else:
                    v = numeric_column(df, c)
                    v = v[~np.isnan(v)]
                    out[c] = (v.size, float(v.sum()))
            return out
        parts = block_partials(dataset, partial)
        stats = {}
        for c in self.columns:
            if strat == "most_frequent":
                counts: Dict[Any, int] = {}
                for p in parts:
                    for k, n in p[c].items():
                        counts[k] = counts.get(k, 0) + int(n)
                stats[c] = max(counts, key=counts.get) if counts else 0
            elif strat == "median":
                merged = np.concatenate([p[c] for p in parts]) \
                    if parts else np.array([0.0])
                stats[c] = float(np.quantile(merged, 0.5)) \
                    if merged.size else 0.0
            else:
                n = sum(p[c][0] for p in parts)
                s = sum(p[c][1] for p in parts)
                stats[c] = s / max(n, 1)
        self.stats_ = stats

    def _transform_pandas(self, df):
        df = df.copy()
        for c in self.columns:
            fill = self.fill_value if self.strategy == "constant" \
                else self.stats_[c]
            df[c] = df[c].fillna(fill)
        return df


class Normalizer(Preprocessor):
    """Row-wise normalization across ``columns`` (reference:
    normalizer.py:9).  Stateless."""

    _is_fittable = False

    def __init__(self, columns: List[str], norm: str = "l2"):
        if norm not in ("l1", "l2", "max"):
            raise ValueError(f"unknown norm {norm!r}")
        self.columns = list(columns)
        self.norm = norm

    def _transform_pandas(self, df):
        df = df.copy()
        mat = df[self.columns].to_numpy(dtype=np.float64)
        if self.norm == "l2":
            d = np.sqrt((mat ** 2).sum(axis=1))
        elif self.norm == "l1":
            d = np.abs(mat).sum(axis=1)
        else:
            d = np.abs(mat).max(axis=1)
        d = np.where(d > 0, d, 1.0)
        mat = mat / d[:, None]
        for i, c in enumerate(self.columns):
            df[c] = mat[:, i]
        return df


class PowerTransformer(Preprocessor):
    """Yeo-Johnson / Box-Cox with a GIVEN power (reference:
    transformer.py:9 — the reference also takes the exponent as config,
    it does not estimate it).  Stateless."""

    _is_fittable = False

    def __init__(self, columns: List[str], power: float,
                 method: str = "yeo-johnson"):
        if method not in ("yeo-johnson", "box-cox"):
            raise ValueError(f"unknown method {method!r}")
        self.columns = list(columns)
        self.power = power
        self.method = method

    def _transform_pandas(self, df):
        df = df.copy()
        lam = self.power
        for c in self.columns:
            x = df[c].to_numpy(dtype=np.float64)
            if self.method == "box-cox":
                y = np.log(x) if lam == 0 else (x ** lam - 1) / lam
            else:
                pos = x >= 0
                if lam == 0:
                    yp = np.log1p(np.where(pos, x, 0.0))
                else:
                    yp = ((np.where(pos, x, 0.0) + 1) ** lam - 1) / lam
                if lam == 2:
                    yn = -np.log1p(np.where(pos, 0.0, -x))
                else:
                    yn = -(((np.where(pos, 0.0, -x) + 1) ** (2 - lam)
                            - 1) / (2 - lam))
                y = np.where(pos, yp, yn)
            df[c] = y
        return df


class UniformKBinsDiscretizer(Preprocessor):
    """Equal-width binning: fit min/max, transform → bin index
    (reference: discretizer.py UniformKBinsDiscretizer)."""

    def __init__(self, columns: List[str], bins: int):
        self.columns = list(columns)
        self.bins = bins

    def _fit(self, dataset: Any) -> None:
        scaler = MinMaxScaler(self.columns)
        scaler._fit(dataset)
        stats = {}
        for c in self.columns:
            lo, hi = scaler.stats_[c]
            stats[c] = np.linspace(lo, hi, self.bins + 1)[1:-1]
        self.stats_ = stats

    def _transform_pandas(self, df):
        df = df.copy()
        for c in self.columns:
            df[c] = np.digitize(df[c].to_numpy(dtype=np.float64),
                                self.stats_[c])
        return df


class CustomKBinsDiscretizer(Preprocessor):
    """Binning with caller-provided edges (reference: discretizer.py
    CustomKBinsDiscretizer).  Stateless."""

    _is_fittable = False

    def __init__(self, columns: List[str], bins: Dict[str, List[float]]):
        self.columns = list(columns)
        self.bins = {c: np.asarray(b, dtype=np.float64)
                     for c, b in bins.items()}

    def _transform_pandas(self, df):
        df = df.copy()
        for c in self.columns:
            # caller edges include the outer bounds (np.histogram style):
            # interior edges are what digitize wants
            df[c] = np.digitize(df[c].to_numpy(dtype=np.float64),
                                self.bins[c][1:-1])
        return df


class Concatenator(Preprocessor):
    """Pack numeric columns into one ndarray column (reference:
    concatenator.py:9 — the trainer-ingest adapter).  Stateless."""

    _is_fittable = False

    def __init__(self, output_column_name: str = "concat",
                 include: Optional[List[str]] = None,
                 exclude: Optional[List[str]] = None,
                 dtype: Any = np.float32):
        self.output_column_name = output_column_name
        self.include = list(include) if include else None
        self.exclude = set(exclude or ())
        self.dtype = dtype

    def _transform_pandas(self, df):
        cols = self.include if self.include is not None else \
            [c for c in df.columns if c not in self.exclude]
        mat = df[cols].to_numpy(dtype=self.dtype)
        out = df.drop(columns=cols)
        out = out.copy()
        out[self.output_column_name] = list(mat)
        return out
