"""Categorical encoders (reference:
/root/reference/python/ray/data/preprocessors/encoder.py:15 —
OrdinalEncoder/OneHotEncoder/MultiHotEncoder/LabelEncoder/Categorizer).

Fit scans gather per-block unique-value sets; category order is sorted
(the reference's convention), so the mapping is deterministic across
block orders and cluster sizes.  Unseen values at transform time encode
as the reference does: null for ordinal/label, all-zeros for one-hot.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .base import Preprocessor, block_partials


def _fit_uniques(dataset: Any, columns: List[str],
                 of_lists: bool = False) -> Dict[str, List[Any]]:
    def partial(df):
        out = {}
        for c in columns:
            vals = df[c].dropna()
            if of_lists:
                seen = set()
                for row in vals:
                    seen.update(row)
                out[c] = sorted(seen)
            else:
                out[c] = sorted(vals.unique().tolist())
        return out
    merged: Dict[str, set] = {c: set() for c in columns}
    for p in block_partials(dataset, partial):
        for c in columns:
            merged[c].update(p[c])
    return {c: sorted(merged[c]) for c in columns}


class OrdinalEncoder(Preprocessor):
    """category → sorted-order int; unseen → NaN."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)

    def _fit(self, dataset: Any) -> None:
        uniq = _fit_uniques(dataset, self.columns)
        self.stats_ = {c: {v: i for i, v in enumerate(vals)}
                       for c, vals in uniq.items()}

    def _transform_pandas(self, df):
        df = df.copy()
        for c in self.columns:
            df[c] = df[c].map(self.stats_[c])
        return df


class OneHotEncoder(Preprocessor):
    """category column → one 0/1 column per category, named
    ``{col}_{value}``; unseen rows get all zeros."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)

    def _fit(self, dataset: Any) -> None:
        self.stats_ = _fit_uniques(dataset, self.columns)

    def _transform_pandas(self, df):
        df = df.copy()
        for c in self.columns:
            col = df[c]
            for v in self.stats_[c]:
                df[f"{c}_{v}"] = (col == v).astype(np.int64)
            df = df.drop(columns=[c])
        return df


class MultiHotEncoder(Preprocessor):
    """list-valued column → multi-hot count vector (reference:
    encoder.py MultiHotEncoder)."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)

    def _fit(self, dataset: Any) -> None:
        self.stats_ = _fit_uniques(dataset, self.columns, of_lists=True)

    def _transform_pandas(self, df):
        df = df.copy()
        for c in self.columns:
            index = {v: i for i, v in enumerate(self.stats_[c])}
            k = len(index)

            def encode(row, _index=index, _k=k):
                vec = np.zeros(_k, dtype=np.int64)
                for item in (row or ()):
                    i = _index.get(item)
                    if i is not None:
                        vec[i] += 1
                return vec
            df[c] = df[c].map(encode)
        return df


class LabelEncoder(Preprocessor):
    """Single label column → sorted-order int, with
    :meth:`inverse_transform_batch` for decoding predictions."""

    def __init__(self, label_column: str):
        self.label_column = label_column

    def _fit(self, dataset: Any) -> None:
        uniq = _fit_uniques(dataset, [self.label_column])
        self.stats_ = {v: i for i, v in
                       enumerate(uniq[self.label_column])}
        self.classes_ = list(uniq[self.label_column])

    def _transform_pandas(self, df):
        df = df.copy()
        df[self.label_column] = df[self.label_column].map(self.stats_)
        return df

    def inverse_transform_batch(self, labels) -> np.ndarray:
        self._check_fitted()
        classes = np.asarray(self.classes_, dtype=object)
        return classes[np.asarray(labels, dtype=np.int64)]


class Categorizer(Preprocessor):
    """Columns → pandas Categorical dtype with dataset-wide category
    sets (reference: encoder.py Categorizer — the GBDT-ingest enabler)."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)

    def _fit(self, dataset: Any) -> None:
        self.stats_ = _fit_uniques(dataset, self.columns)

    def _transform_pandas(self, df):
        import pandas as pd
        df = df.copy()
        for c in self.columns:
            df[c] = pd.Categorical(df[c], categories=self.stats_[c])
        return df
