"""AIR preprocessors: fit on a Dataset, transform Datasets and batches,
ride Checkpoints into BatchPredictor/Serve.

Capability mirror of
/root/reference/python/ray/data/preprocessors/__init__.py:1.
"""

from .base import (BatchMapper, Chain, Preprocessor,
                   PreprocessorNotFittedError)
from .encoders import (Categorizer, LabelEncoder, MultiHotEncoder,
                       OneHotEncoder, OrdinalEncoder)
from .scalers import (Concatenator, CustomKBinsDiscretizer, MaxAbsScaler,
                      MinMaxScaler, Normalizer, PowerTransformer,
                      RobustScaler, SimpleImputer, StandardScaler,
                      UniformKBinsDiscretizer)
from .text import (CountVectorizer, FeatureHasher, HashingVectorizer,
                   Tokenizer)

__all__ = [
    "BatchMapper", "Categorizer", "Chain", "Concatenator",
    "CountVectorizer", "CustomKBinsDiscretizer", "FeatureHasher",
    "HashingVectorizer", "LabelEncoder", "MaxAbsScaler", "MinMaxScaler",
    "MultiHotEncoder", "Normalizer", "OneHotEncoder", "OrdinalEncoder",
    "PowerTransformer", "Preprocessor", "PreprocessorNotFittedError",
    "RobustScaler", "SimpleImputer", "StandardScaler", "Tokenizer",
    "UniformKBinsDiscretizer",
]
