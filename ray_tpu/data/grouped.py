"""GroupBy: hash-partition then per-partition aggregate.

Capability mirror of the reference's `data/grouped_dataset.py` (sum/min/
max/mean/std/count + map_groups), built on the same two-stage all-to-all
machinery as shuffle.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import api
from .block import Block, BlockAccessor, BlockMetadata


def _hash_partition(block: Block, key: str, n: int) -> List[Block]:
    acc = BlockAccessor(block)
    rows = list(acc.iter_rows())
    parts: List[List[int]] = [[] for _ in range(n)]
    for i, r in enumerate(rows):
        parts[hash(r[key]) % n].append(i)
    return [acc.take(p) for p in parts]


def _agg_partition(key: str, aggs: List[Tuple[str, Optional[str]]],
                   *parts: Block) -> Tuple[Block, BlockMetadata]:
    import pandas as pd
    dfs = [BlockAccessor(p).to_pandas() for p in parts]
    df = pd.concat(dfs, ignore_index=True) if dfs else pd.DataFrame()
    if df.empty:
        out = df
    else:
        groups = df.groupby(key, sort=True)
        cols: Dict[str, Any] = {}
        for op, col in aggs:
            if op == "count":
                cols["count()"] = groups.size()
                continue
            target_cols = [col] if col else [
                c for c in df.columns
                if c != key and np.issubdtype(df[c].dtype, np.number)]
            for c in target_cols:
                series = getattr(groups[c], op if op != "std" else "std")()
                cols[f"{op}({c})"] = series
        out = pd.DataFrame(cols).reset_index()
    return out, BlockAccessor(out).metadata()


def _map_groups(key: str, fn_bytes: bytes,
                *parts: Block) -> Tuple[Block, BlockMetadata]:
    import pandas as pd

    from ..core.serialization import loads_function
    fn = loads_function(fn_bytes)
    dfs = [BlockAccessor(p).to_pandas() for p in parts]
    df = pd.concat(dfs, ignore_index=True) if dfs else pd.DataFrame()
    outs = []
    if not df.empty:
        for _, group in df.groupby(key, sort=True):
            outs.append(BlockAccessor(
                _normalize(fn(group))).to_pandas())
    out = pd.concat(outs, ignore_index=True) if outs else df
    return out, BlockAccessor(out).metadata()


def _normalize(res):
    import pandas as pd
    if isinstance(res, dict):
        return pd.DataFrame({k: np.atleast_1d(v) for k, v in res.items()})
    return res


class GroupedData:
    def __init__(self, dataset, key: str):
        self._ds = dataset
        self._key = key

    def _partitioned(self, n: int):
        from .dataset import _remote
        part = _remote(f"hashpart/{n}", _hash_partition, num_returns=n)
        parts = [part.remote(b, self._key, n) for b in self._ds._blocks]
        if n == 1:
            parts = [[p] for p in parts]
        return parts

    def _aggregate(self, aggs: List[Tuple[str, Optional[str]]]):
        from .dataset import Dataset, _remote
        n = max(min(self._ds.num_blocks(), 8), 1)
        parts = self._partitioned(n)
        agg = _remote("aggpart", _agg_partition, num_returns=2)
        refs, metas = [], []
        for j in range(n):
            pair = agg.remote(self._key, aggs, *[p[j] for p in parts])
            refs.append(pair[0])
            metas.append(pair[1])
        return Dataset(refs, api.get(metas, timeout=600.0))

    def count(self):
        return self._aggregate([("count", None)])

    def sum(self, column: Optional[str] = None):
        return self._aggregate([("sum", column)])

    def min(self, column: Optional[str] = None):
        return self._aggregate([("min", column)])

    def max(self, column: Optional[str] = None):
        return self._aggregate([("max", column)])

    def mean(self, column: Optional[str] = None):
        return self._aggregate([("mean", column)])

    def std(self, column: Optional[str] = None):
        return self._aggregate([("std", column)])

    def aggregate(self, *aggs: Tuple[str, Optional[str]]):
        return self._aggregate(list(aggs))

    def map_groups(self, fn: Callable):
        from ..core.serialization import dumps_function
        from .dataset import Dataset, _remote
        n = max(min(self._ds.num_blocks(), 8), 1)
        parts = self._partitioned(n)
        blob = dumps_function(fn)
        mg = _remote("mapgroups", _map_groups, num_returns=2)
        refs, metas = [], []
        for j in range(n):
            pair = mg.remote(self._key, blob, *[p[j] for p in parts])
            refs.append(pair[0])
            metas.append(pair[1])
        return Dataset(refs, api.get(metas, timeout=600.0))
