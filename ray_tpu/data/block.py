"""Blocks: the unit of distributed data.

Capability mirror of the reference's `data/block.py:101,211,235` — a Block
is a pyarrow Table, pandas DataFrame, or Python list; `BlockAccessor`
dispatches format-specific ops; `BlockMetadata` carries rows/bytes/schema
for planning without touching data.
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np

Block = Union["pyarrow.Table", "pandas.DataFrame", List[Any]]


@dataclasses.dataclass
class BlockMetadata:
    num_rows: Optional[int] = None
    size_bytes: Optional[int] = None
    schema: Optional[Any] = None
    input_files: Optional[List[str]] = None


class BlockAccessor:
    """Format-agnostic operations over one block."""

    def __init__(self, block: Block):
        self._block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    # -- introspection ------------------------------------------------------
    def num_rows(self) -> int:
        b = self._block
        if _is_arrow(b):
            return b.num_rows
        if _is_pandas(b):
            return len(b)
        return len(b)

    def size_bytes(self) -> int:
        b = self._block
        if _is_arrow(b):
            return b.nbytes
        if _is_pandas(b):
            return int(b.memory_usage(index=True, deep=True).sum())
        return sum(sys.getsizeof(x) for x in b)

    def schema(self):
        b = self._block
        if _is_arrow(b):
            return b.schema
        if _is_pandas(b):
            return list(b.dtypes.items())
        return type(b[0]).__name__ if b else None

    def metadata(self, input_files=None) -> BlockMetadata:
        return BlockMetadata(num_rows=self.num_rows(),
                             size_bytes=self.size_bytes(),
                             schema=self.schema(),
                             input_files=input_files)

    # -- conversions --------------------------------------------------------
    def to_arrow(self):
        import pyarrow as pa
        b = self._block
        if _is_arrow(b):
            return b
        if _is_pandas(b):
            return pa.Table.from_pandas(b, preserve_index=False)
        if b and isinstance(b[0], dict):
            return pa.Table.from_pylist(b)
        return pa.table({"value": b})

    def to_pandas(self):
        import pandas as pd
        b = self._block
        if _is_arrow(b):
            return b.to_pandas()
        if _is_pandas(b):
            return b
        if b and isinstance(b[0], dict):
            return pd.DataFrame(b)
        return pd.DataFrame({"value": b})

    def to_numpy(self, column: Optional[str] = None):
        b = self._block
        if _is_arrow(b):
            if column:
                return b.column(column).to_numpy(zero_copy_only=False)
            return {name: b.column(name).to_numpy(zero_copy_only=False)
                    for name in b.column_names}
        if _is_pandas(b):
            if column:
                return b[column].to_numpy()
            return {c: b[c].to_numpy() for c in b.columns}
        if b and isinstance(b[0], dict):
            keys = b[0].keys()
            return {k: np.asarray([row[k] for row in b]) for k in keys}
        return np.asarray(b)

    def to_batch(self, batch_format: str):
        if batch_format in ("native", "default"):
            return self._block
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format == "pyarrow":
            return self.to_arrow()
        if batch_format == "numpy":
            return self.to_numpy()
        raise ValueError(f"unknown batch_format {batch_format!r}")

    # -- slicing / iteration ------------------------------------------------
    def slice(self, start: int, end: int) -> Block:
        b = self._block
        if _is_arrow(b):
            return b.slice(start, end - start)
        if _is_pandas(b):
            return b.iloc[start:end]
        return b[start:end]

    def take(self, indices: List[int]) -> Block:
        b = self._block
        if _is_arrow(b):
            import pyarrow as pa
            return b.take(pa.array(indices))
        if _is_pandas(b):
            return b.iloc[indices]
        return [b[i] for i in indices]

    def iter_rows(self) -> Iterator[Any]:
        b = self._block
        if _is_arrow(b):
            yield from b.to_pylist()
        elif _is_pandas(b):
            for _, row in b.iterrows():
                yield row.to_dict()
        else:
            yield from b

    def sample(self, n: int, sort_key: Optional[str]) -> List[Any]:
        rows = self.num_rows()
        if rows == 0:
            return []
        idx = np.random.default_rng(0).choice(
            rows, size=min(n, rows), replace=False)
        picked = BlockAccessor(self.take([int(i) for i in idx]))
        if sort_key is None:
            return list(picked.iter_rows())
        return [r[sort_key] for r in picked.iter_rows()]

    @staticmethod
    def combine(blocks: List[Block]) -> Block:
        """Concatenate same-format blocks."""
        blocks = [b for b in blocks
                  if BlockAccessor(b).num_rows() > 0] or blocks[:1]
        first = blocks[0]
        if _is_arrow(first):
            import pyarrow as pa
            return pa.concat_tables(blocks)
        if _is_pandas(first):
            import pandas as pd
            return pd.concat(blocks, ignore_index=True)
        out: List[Any] = []
        for b in blocks:
            out.extend(b)
        return out

    @staticmethod
    def empty_like(block: Block) -> Block:
        if _is_arrow(block):
            return block.slice(0, 0)
        if _is_pandas(block):
            return block.iloc[0:0]
        return []


def _is_arrow(b) -> bool:
    mod = type(b).__module__
    return mod.startswith("pyarrow") and type(b).__name__ == "Table"


def _is_pandas(b) -> bool:
    return type(b).__module__.startswith("pandas") and \
        type(b).__name__ == "DataFrame"


def batch_to_block(batch: Any) -> Block:
    """Normalize a user map_batches return value into a block."""
    if isinstance(batch, dict):  # numpy dict batch
        import pandas as pd
        return pd.DataFrame({k: list(np.asarray(v)) for k, v in
                             batch.items()})
    return batch
