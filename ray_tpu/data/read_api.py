"""Dataset creation: in-memory sources and lazy file datasources.

Capability mirror of the reference's `data/read_api.py` + `data/datasource/`
(range/from_items/from_pandas/from_numpy/from_arrow, parquet/csv/json/text/
binary readers, read_datasource).  File and range reads are LAZY: they
build ReadTasks on an ExecutionPlan, so the read fuses with downstream map
stages into one task per file (reference: `data/_internal/plan.py:74`).
"""

from __future__ import annotations

import builtins
from typing import Any, List

from .. import api
from .block import BlockAccessor
from .dataset import Dataset
from .datasource import (BinaryDatasource, CSVDatasource, Datasource,
                         JSONDatasource, NumpyDatasource,
                         ParquetDatasource, RangeDatasource,
                         TextDatasource)
from .plan import ExecutionPlan


def _put_blocks(blocks: List[Any]) -> Dataset:
    refs = [api.put(b) for b in blocks]
    meta = [BlockAccessor(b).metadata() for b in blocks]
    return Dataset(refs, meta)


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    n = max(1, min(parallelism, len(items) or 1))
    size = -(-len(items) // n) or 1
    blocks = [items[i:i + size]
              for i in builtins.range(0, max(len(items), 1), size)]
    return _put_blocks(blocks or [[]])


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    return read_datasource(RangeDatasource(n), parallelism=parallelism,
                           _name="range")


def range_tensor(n: int, *, shape=(1,), parallelism: int = 8) -> Dataset:
    return read_datasource(RangeDatasource(n, tensor_shape=shape),
                           parallelism=parallelism, _name="range_tensor")


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return _put_blocks(dfs)


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return _put_blocks(tables)


def from_numpy(arrays) -> Dataset:
    import pandas as pd
    if not isinstance(arrays, list):
        arrays = [arrays]
    return _put_blocks([pd.DataFrame({"data": list(a)}) for a in arrays])


# -- lazy reads --------------------------------------------------------------

def read_datasource(datasource: Datasource, *, parallelism: int = 8,
                    _name: str = "read", **read_args) -> Dataset:
    """Build a lazy dataset from any Datasource's ReadTasks."""
    tasks = datasource.prepare_read(parallelism, **read_args)
    return Dataset.from_plan(ExecutionPlan.from_read_tasks(tasks, _name))


def read_parquet(paths, **kwargs) -> Dataset:
    return read_datasource(ParquetDatasource(paths, **kwargs),
                           _name="read_parquet")


def read_csv(paths, **kwargs) -> Dataset:
    return read_datasource(CSVDatasource(paths, **kwargs), _name="read_csv")


def read_numpy(paths, **kwargs) -> Dataset:
    """.npy files, rows along axis 0 (reference: `ray.data.read_numpy`
    — the read counterpart of `Dataset.write_numpy`)."""
    return read_datasource(NumpyDatasource(paths, **kwargs),
                           _name="read_numpy")


def read_json(paths, **kwargs) -> Dataset:
    return read_datasource(JSONDatasource(paths, **kwargs),
                           _name="read_json")


def read_text(paths, **kwargs) -> Dataset:
    return read_datasource(TextDatasource(paths, **kwargs),
                           _name="read_text")


def read_binary_files(paths, **kwargs) -> Dataset:
    return read_datasource(BinaryDatasource(paths, **kwargs),
                           _name="read_binary_files")


def read_tfrecords(paths, **kwargs) -> Dataset:
    """TFRecord files of `tf.train.Example` records (reference:
    `ray.data.read_tfrecords`) — no TensorFlow needed; the container
    and proto codec are implemented in data/tfrecords.py."""
    from .tfrecords import TFRecordDatasource
    return read_datasource(TFRecordDatasource(paths, **kwargs),
                           _name="read_tfrecords")


def read_images(paths, **kwargs) -> Dataset:
    """Image files → rows of decoded HWC uint8 arrays (reference:
    `ray.data.read_images`)."""
    from .datasource import ImageDatasource
    return read_datasource(ImageDatasource(paths, **kwargs),
                           _name="read_images")
