"""Dataset creation: in-memory sources and file datasources.

Capability mirror of the reference's `data/read_api.py` + `data/datasource/`
(range/from_items/from_pandas/from_numpy/from_arrow, parquet/csv/json/text/
binary readers).  File reads fan out one runtime task per file.
"""

from __future__ import annotations

import builtins
import glob as _glob
import os
from typing import Any, List, Optional

import numpy as np

from .. import api
from .block import BlockAccessor, BlockMetadata
from .dataset import Dataset, _remote


def _put_blocks(blocks: List[Any]) -> Dataset:
    refs = [api.put(b) for b in blocks]
    meta = [BlockAccessor(b).metadata() for b in blocks]
    return Dataset(refs, meta)


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    n = max(1, min(parallelism, len(items) or 1))
    size = -(-len(items) // n) or 1
    blocks = [items[i:i + size]
              for i in builtins.range(0, max(len(items), 1), size)]
    return _put_blocks(blocks or [[]])


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    import pandas as pd
    n_blocks = max(1, min(parallelism, n or 1))
    bounds = np.linspace(0, n, n_blocks + 1).astype(int)
    blocks = [pd.DataFrame({"id": np.arange(lo, hi)})
              for lo, hi in zip(bounds[:-1], bounds[1:])]
    return _put_blocks(blocks)


def range_tensor(n: int, *, shape=(1,), parallelism: int = 8) -> Dataset:
    import pandas as pd
    n_blocks = max(1, min(parallelism, n or 1))
    bounds = np.linspace(0, n, n_blocks + 1).astype(int)
    blocks = []
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        idx = np.arange(lo, hi)
        data = (idx.reshape((-1,) + (1,) * len(shape)) *
                np.ones(shape)[None])
        blocks.append(pd.DataFrame(
            {"data": list(data)}))
    return _put_blocks(blocks)


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return _put_blocks(dfs)


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return _put_blocks(tables)


def from_numpy(arrays) -> Dataset:
    import pandas as pd
    if not isinstance(arrays, list):
        arrays = [arrays]
    return _put_blocks([pd.DataFrame({"data": list(a)}) for a in arrays])


# -- file readers -----------------------------------------------------------

def _expand(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                f for f in _glob.glob(os.path.join(p, "**"), recursive=True)
                if os.path.isfile(f)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def _read_file(path: str, fmt: str, kwargs: dict):
    import pandas as pd
    if fmt == "parquet":
        block = pd.read_parquet(path, **kwargs)
    elif fmt == "csv":
        block = pd.read_csv(path, **kwargs)
    elif fmt == "json":
        block = pd.read_json(path, orient="records", lines=True, **kwargs)
    elif fmt == "text":
        with open(path, "r", errors="replace") as f:
            block = [line.rstrip("\n") for line in f]
    elif fmt == "binary":
        with open(path, "rb") as f:
            block = [f.read()]
    else:
        raise ValueError(fmt)
    meta = BlockAccessor(block).metadata(input_files=[path])
    return block, meta


def _read(paths, fmt: str, **kwargs) -> Dataset:
    files = _expand(paths)
    f = _remote("read_file", _read_file, num_returns=2)
    pairs = [f.remote(p, fmt, kwargs) for p in files]
    refs = [p[0] for p in pairs]
    meta = api.get([p[1] for p in pairs], timeout=600.0)
    return Dataset(refs, meta)


def read_parquet(paths, **kwargs) -> Dataset:
    return _read(paths, "parquet", **kwargs)


def read_csv(paths, **kwargs) -> Dataset:
    return _read(paths, "csv", **kwargs)


def read_json(paths, **kwargs) -> Dataset:
    return _read(paths, "json", **kwargs)


def read_text(paths, **kwargs) -> Dataset:
    return _read(paths, "text", **kwargs)


def read_binary_files(paths, **kwargs) -> Dataset:
    return _read(paths, "binary", **kwargs)
