"""Pluggable read/write datasources.

Capability mirror of the reference's `data/datasource/datasource.py:1`
(Datasource ABC: ``prepare_read`` returning ReadTasks, ``do_write`` fanning
out one write task per block) and `datasource/file_based_datasource.py`
(path expansion + per-file read/write).  A ReadTask is a zero-arg callable
producing one block; the execution plan fuses it with downstream map stages
so read->map->filter chains run as ONE task per file.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, List, Optional

from .. import api
from .block import Block, BlockAccessor


class ReadTask:
    """One unit of lazy input: call it to produce a block."""

    def __init__(self, read_fn: Callable[[], Block],
                 input_files: Optional[List[str]] = None):
        self._read_fn = read_fn
        self.input_files = input_files

    def __call__(self) -> Block:
        return self._read_fn()


class Datasource:
    """Read/write extension point (subclass and override)."""

    def prepare_read(self, parallelism: int, **read_args) -> List[ReadTask]:
        raise NotImplementedError

    def write_block(self, block: Block, path: str, index: int,
                    **write_args) -> Any:
        """Write ONE block; runs inside a task. Returns a result token."""
        raise NotImplementedError

    def do_write(self, block_refs: List[Any], path: str,
                 **write_args) -> List[Any]:
        """Fan out one write task per block and collect results."""
        from .dataset import _remote
        f = _remote("ds_write", _datasource_write_block)
        from ..core.serialization import dumps_function
        blob = dumps_function(self.write_block)
        results = api.get(
            [f.remote(blob, b, path, i, write_args)
             for i, b in enumerate(block_refs)], timeout=600.0)
        self.on_write_complete(results)
        return results

    def on_write_complete(self, write_results: List[Any]) -> None:
        pass

    def on_write_failed(self, error: Exception) -> None:
        pass


def _datasource_write_block(fn_blob: bytes, block: Block, path: str,
                            index: int, write_args: Dict[str, Any]) -> Any:
    from ..core.serialization import loads_function
    write_block = loads_function(fn_blob)
    return write_block(block, path, index, **write_args)


# -- file-based datasources --------------------------------------------------


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                f for f in _glob.glob(os.path.join(p, "**"), recursive=True)
                if os.path.isfile(f)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


class FileBasedDatasource(Datasource):
    """One ReadTask per file; subclasses define the per-file (de)serializer."""

    _FILE_EXT = "dat"

    def __init__(self, paths=None, **read_args):
        self._paths = paths
        self._read_args = read_args

    def _read_file(self, path: str, **read_args) -> Block:
        raise NotImplementedError

    def _write_file(self, df, path: str, **write_args) -> None:
        raise NotImplementedError

    def prepare_read(self, parallelism: int, **read_args) -> List[ReadTask]:
        args = {**self._read_args, **read_args}
        files = _expand_paths(self._paths)
        reader = self._read_file
        return [ReadTask((lambda p=path: reader(p, **args)),
                         input_files=[path])
                for path in files]

    def write_block(self, block: Block, path: str, index: int,
                    **write_args) -> str:
        out = os.path.join(path, f"part-{index:05d}.{self._FILE_EXT}")
        self._write_file(BlockAccessor(block).to_pandas(), out, **write_args)
        return out


class ParquetDatasource(FileBasedDatasource):
    _FILE_EXT = "parquet"

    def _read_file(self, path: str, **kw) -> Block:
        import pandas as pd
        return pd.read_parquet(path, **kw)

    def _write_file(self, df, path: str, **kw) -> None:
        df.to_parquet(path, **kw)


class CSVDatasource(FileBasedDatasource):
    _FILE_EXT = "csv"

    def _read_file(self, path: str, **kw) -> Block:
        import pandas as pd
        return pd.read_csv(path, **kw)

    def _write_file(self, df, path: str, **kw) -> None:
        df.to_csv(path, index=False, **kw)


class NumpyDatasource(FileBasedDatasource):
    """.npy files ⇄ blocks (reference:
    `data/datasource/numpy_datasource.py` — the read counterpart of
    `Dataset.write_numpy`).

    Structured arrays (what ``_write_file`` and column-less
    ``write_numpy`` produce via ``to_records``) restore their column
    names and dtypes; plain arrays become rows along axis 0 under
    ``column`` (default ``"data"``, matching ``from_numpy``).
    ``allow_pickle`` defaults True because ``np.save`` pickles
    object-dtype columns without asking — the write side already
    committed to it."""

    _FILE_EXT = "npy"

    def _read_file(self, path: str, column: str = "data",
                   allow_pickle: bool = True, **kw) -> Block:
        import numpy as np
        import pandas as pd
        arr = np.load(path, allow_pickle=allow_pickle, **kw)
        if arr.dtype.names:       # structured: columns round-trip
            return pd.DataFrame.from_records(arr)
        return pd.DataFrame({column: list(np.atleast_1d(arr))})

    def _write_file(self, df, path: str, **kw) -> None:
        import numpy as np
        # to_records keeps column names/dtypes — the same fidelity the
        # CSV/JSON/Parquet datasources in this file provide
        np.save(path, df.to_records(index=False), **kw)


class JSONDatasource(FileBasedDatasource):
    _FILE_EXT = "json"

    def _read_file(self, path: str, **kw) -> Block:
        import pandas as pd
        return pd.read_json(path, orient="records", lines=True, **kw)

    def _write_file(self, df, path: str, **kw) -> None:
        df.to_json(path, orient="records", lines=True, **kw)


class TextDatasource(FileBasedDatasource):
    _FILE_EXT = "txt"

    def _read_file(self, path: str, **kw) -> Block:
        with open(path, "r", errors="replace") as f:
            return [line.rstrip("\n") for line in f]

    def _write_file(self, df, path: str, **kw) -> None:
        with open(path, "w") as f:
            for v in df[df.columns[0]]:
                f.write(f"{v}\n")


class BinaryDatasource(FileBasedDatasource):
    _FILE_EXT = "bin"

    def _read_file(self, path: str, **kw) -> Block:
        with open(path, "rb") as f:
            return [f.read()]


class RangeDatasource(Datasource):
    """Lazy integer range (reference: `datasource.RangeDatasource`)."""

    def __init__(self, n: int, tensor_shape=None):
        self._n = n
        self._shape = tensor_shape

    def prepare_read(self, parallelism: int, **read_args) -> List[ReadTask]:
        import numpy as np
        n_blocks = max(1, min(parallelism, self._n or 1))
        bounds = np.linspace(0, self._n, n_blocks + 1).astype(int)
        shape = self._shape

        def make(lo: int, hi: int) -> Callable[[], Block]:
            def read() -> Block:
                import numpy as np
                import pandas as pd
                idx = np.arange(lo, hi)
                if shape is None:
                    return pd.DataFrame({"id": idx})
                data = (idx.reshape((-1,) + (1,) * len(shape)) *
                        np.ones(shape)[None])
                return pd.DataFrame({"data": list(data)})
            return read

        return [ReadTask(make(int(lo), int(hi)))
                for lo, hi in zip(bounds[:-1], bounds[1:])]


class ImageDatasource(FileBasedDatasource):
    """Image files → rows with decoded pixel arrays (reference:
    `data/datasource/image_datasource.py` — `ray.data.read_images`).
    Columns: ``image`` (HWC ndarray, native mode preserved) and
    optionally ``path``; ``size=(H, W)`` resizes on read, ``mode``
    converts (e.g. "RGB", "L"; default None keeps the file's own
    mode/channels).  Directory reads skip non-image files by extension,
    like the reference."""

    _FILE_EXT = "png"
    _IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".gif", ".bmp", ".webp",
                   ".tif", ".tiff")

    def prepare_read(self, parallelism: int, **read_args):
        # extension filtering applies only to files DISCOVERED through
        # directory/glob expansion; a file the user named explicitly is
        # always read (and PIL raises loudly if it isn't an image)
        paths = [self._paths] if isinstance(self._paths, str) \
            else list(self._paths)
        explicit = {p for p in paths
                    if not os.path.isdir(p)
                    and not any(ch in p for ch in "*?[")}
        tasks = super().prepare_read(parallelism, **read_args)
        kept = [t for t in tasks
                if t.input_files[0] in explicit
                or t.input_files[0].lower().endswith(self._IMAGE_EXTS)]
        if not kept:
            raise FileNotFoundError(
                f"no image files ({'/'.join(self._IMAGE_EXTS)}) "
                f"matched {self._paths}")
        return kept

    def _read_file(self, path: str, size=None, mode=None,
                   include_paths: bool = False, **kw):
        import numpy as np
        import pandas as pd
        from PIL import Image
        img = Image.open(path)
        if mode is not None:
            img = img.convert(mode)
        if size is not None:
            img = img.resize((size[1], size[0]))
        row = {"image": [np.asarray(img)]}
        if include_paths:
            row["path"] = [path]
        return pd.DataFrame(row)
