"""Lazy execution plan: stages build up, execute once, fuse where possible.

Capability mirror of the reference's `data/_internal/plan.py:74`
(ExecutionPlan with stage recording + one-to-one stage fusion) and
`data/_internal/stats.py:1` (per-stage wall/rows/bytes).  Transforms append
stages; nothing runs until a consumption op calls ``execute()``.  Chains of
one-to-one stages — including the read itself — fuse into ONE task per
block, so a 10-stage map pipeline holds one set of intermediate refs, not
ten.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, List, Optional, Tuple

from .. import api
from .block import BlockAccessor, BlockMetadata

# -- task bodies (top-level, cloudpickled once) ------------------------------


def _fused_map(fns_blob: bytes, block):
    """Apply a chain of block functions in one task."""
    from ..core.serialization import loads_function
    for fn in loads_function(fns_blob):
        block = fn(block)
    return block, BlockAccessor(block).metadata()


def _fused_read(task_blob: bytes, fns_blob: bytes):
    """Run one ReadTask then the fused downstream chain, all in one task."""
    from ..core.serialization import loads_function
    read_task = loads_function(task_blob)
    block = read_task()
    input_files = getattr(read_task, "input_files", None)
    for fn in loads_function(fns_blob):
        block = fn(block)
    return block, BlockAccessor(block).metadata(input_files=input_files)


# -- stages ------------------------------------------------------------------


class OneToOneStage:
    """A per-block transform; consecutive ones fuse into a single task."""

    fusable = True

    def __init__(self, name: str, block_fn: Callable):
        self.name = name
        self.block_fn = block_fn

    def expected_num_blocks(self, n_in: int) -> int:
        return n_in


class AllToAllStage:
    """A barrier stage (shuffle/sort/repartition) run by a driver-side fn.

    ``fn(refs, meta) -> (refs, meta)`` may submit its own task graph (the
    two-stage shuffle pattern); it cannot fuse with neighbours.
    """

    fusable = False

    def __init__(self, name: str, fn: Callable,
                 num_out: Optional[int] = None):
        self.name = name
        self.fn = fn
        self.num_out = num_out

    def expected_num_blocks(self, n_in: int) -> int:
        return self.num_out if self.num_out is not None else n_in


@dataclasses.dataclass
class StageStats:
    """What one executed stage (or fused stage group) cost."""
    name: str
    wall_s: float
    num_tasks: int
    out_rows: int
    out_bytes: int

    def line(self, index: int) -> str:
        return (f"Stage {index} {self.name}: {self.num_tasks} tasks, "
                f"{self.wall_s:.3f}s wall, rows={self.out_rows}, "
                f"bytes={self.out_bytes}")


class ExecutionPlan:
    """Input blocks (or pending read tasks) + recorded stages + cache."""

    def __init__(self, in_refs: Optional[List[Any]] = None,
                 in_meta: Optional[List[BlockMetadata]] = None,
                 read_tasks: Optional[List[Any]] = None,
                 read_name: str = "read",
                 parent_stats: Optional[List[StageStats]] = None):
        assert (in_refs is None) != (read_tasks is None)
        self._in_refs = in_refs
        self._in_meta = in_meta
        self._read_tasks = read_tasks
        self._read_name = read_name
        self._stages: List[Any] = []
        self._out: Optional[Tuple[List[Any], List[BlockMetadata]]] = None
        self._stats: List[StageStats] = list(parent_stats or [])
        # ancestor plan sharing our input + stage prefix; if it executes
        # first, we start from its cached blocks instead of replaying the
        # whole chain (read included) from scratch
        self._parent: Optional["ExecutionPlan"] = None
        # how many plans branched off this one while it was lazy; >1 means
        # this is a shared branch point that must materialize exactly once
        self._n_children = 0

    # -- construction --------------------------------------------------------
    @classmethod
    def from_blocks(cls, refs: List[Any],
                    meta: Optional[List[BlockMetadata]]) -> "ExecutionPlan":
        plan = cls(in_refs=list(refs),
                   in_meta=list(meta) if meta else
                   [BlockMetadata()] * len(refs))
        plan._out = (plan._in_refs, plan._in_meta)  # already materialized
        return plan

    @classmethod
    def from_read_tasks(cls, tasks: List[Any],
                        name: str = "read") -> "ExecutionPlan":
        return cls(read_tasks=list(tasks), read_name=name)

    def with_stage(self, stage) -> "ExecutionPlan":
        """A new plan extending this one; this plan is never mutated.

        If this plan already executed, the child starts from the cached
        output blocks (a snapshot — shared ancestors never re-run) and
        inherits the full stats lineage.  Otherwise the child shares the
        same input and replays the recorded stage chain plus ``stage``.
        """
        if self._out is not None:
            refs, meta = self._out
            child = ExecutionPlan(in_refs=refs, in_meta=meta,
                                  parent_stats=self._stats)
        elif self._read_tasks is not None:
            child = ExecutionPlan(read_tasks=self._read_tasks,
                                  read_name=self._read_name,
                                  parent_stats=self._stats)
            child._stages = list(self._stages)
            child._parent = self
            self._n_children += 1
        else:
            child = ExecutionPlan(in_refs=self._in_refs,
                                  in_meta=self._in_meta,
                                  parent_stats=self._stats)
            child._stages = list(self._stages)
            child._parent = self
            self._n_children += 1
        child._stages = child._stages + [stage]
        return child

    # -- introspection -------------------------------------------------------
    @property
    def executed(self) -> bool:
        return self._out is not None

    def expected_num_blocks(self) -> int:
        n = (len(self._read_tasks) if self._read_tasks is not None
             else len(self._in_refs))
        for s in self._stages:
            n = s.expected_num_blocks(n)
        return n

    def stage_names(self) -> List[str]:
        names = ([self._read_name] if self._read_tasks is not None else [])
        return names + [s.name for s in self._stages]

    def stats(self) -> List[StageStats]:
        return list(self._stats)

    # -- execution -----------------------------------------------------------
    def execute(self) -> Tuple[List[Any], List[BlockMetadata]]:
        if self._out is not None:
            return self._out
        from ..core.serialization import dumps_function
        from .dataset import _remote

        # Materialize the nearest shared branch point first: siblings
        # forked from the same lazy plan must not each replay the read.
        # Its execute() recurses for deeper shared ancestors.
        node = self._parent
        while node is not None and node._out is None:
            if node._n_children > 1:
                node.execute()
                break
            node = node._parent

        # Reuse the nearest executed ancestor's cached blocks: by
        # construction every ancestor's stage list is a prefix of ours,
        # so only the suffix (plus no re-read) needs to run.
        node = self._parent
        while node is not None and node._out is None:
            node = node._parent
        if node is not None:
            refs, meta = node._out
            self._stats = list(node._stats)
            i = len(node._stages)
            stages = list(self._stages)
            return self._run_stages(stages, i, refs, meta)

        stages = list(self._stages)
        i = 0
        if self._read_tasks is not None:
            # fuse the read with every leading one-to-one stage
            fuse: List[Any] = []
            while i < len(stages) and stages[i].fusable:
                fuse.append(stages[i])
                i += 1
            name = "->".join([self._read_name] + [s.name for s in fuse])
            t0 = time.perf_counter()
            fns_blob = dumps_function([s.block_fn for s in fuse])
            f = _remote("fused_read", _fused_read, num_returns=2)
            pairs = [f.remote(dumps_function(task), fns_blob)
                     for task in self._read_tasks]
            refs = [p[0] for p in pairs]
            meta = api.get([p[1] for p in pairs], timeout=600.0)
            self._record(name, t0, len(refs), meta)
        else:
            refs, meta = self._in_refs, self._in_meta
        return self._run_stages(stages, i, refs, meta)

    def _run_stages(self, stages: List[Any], i: int, refs: List[Any],
                    meta: List[BlockMetadata]):
        from ..core.serialization import dumps_function
        from .dataset import _remote

        while i < len(stages):
            if stages[i].fusable:
                fuse = []
                while i < len(stages) and stages[i].fusable:
                    fuse.append(stages[i])
                    i += 1
                name = "->".join(s.name for s in fuse)
                t0 = time.perf_counter()
                fns_blob = dumps_function([s.block_fn for s in fuse])
                f = _remote("fused_map", _fused_map, num_returns=2)
                pairs = [f.remote(fns_blob, b) for b in refs]
                refs = [p[0] for p in pairs]
                meta = api.get([p[1] for p in pairs], timeout=600.0)
                self._record(name, t0, len(refs), meta)
            else:
                stage = stages[i]
                i += 1
                t0 = time.perf_counter()
                refs, meta = stage.fn(refs, meta)
                self._record(stage.name, t0, len(refs), meta)

        self._out = (refs, meta)
        return self._out

    def _record(self, name: str, t0: float, n_tasks: int,
                meta: List[BlockMetadata]) -> None:
        self._stats.append(StageStats(
            name=name, wall_s=time.perf_counter() - t0, num_tasks=n_tasks,
            out_rows=sum(m.num_rows or 0 for m in meta),
            out_bytes=sum(m.size_bytes or 0 for m in meta)))
