"""Windowed streaming over datasets.

Capability mirror of the reference's `data/dataset_pipeline.py` (window /
repeat / per-window transforms / streaming iteration) — overlap ingest with
compute by handing Train one window at a time.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional


class DatasetPipeline:
    def __init__(self, window_fns: List[Callable[[], Any]]):
        self._window_fns = list(window_fns)

    @classmethod
    def from_windows(cls, windows: List[Any]) -> "DatasetPipeline":
        return cls([(lambda w=w: w) for w in windows])

    def num_windows(self) -> int:
        return len(self._window_fns)

    def iter_datasets(self) -> Iterator[Any]:
        for fn in self._window_fns:
            yield fn()

    # transforms compose lazily per window
    def _chain(self, op: Callable[[Any], Any]) -> "DatasetPipeline":
        return DatasetPipeline(
            [(lambda fn=fn: op(fn())) for fn in self._window_fns])

    def map_batches(self, fn: Callable, **kw) -> "DatasetPipeline":
        return self._chain(lambda ds: ds.map_batches(fn, **kw))

    def map(self, fn: Callable) -> "DatasetPipeline":
        return self._chain(lambda ds: ds.map(fn))

    def filter(self, fn: Callable) -> "DatasetPipeline":
        return self._chain(lambda ds: ds.filter(fn))

    def random_shuffle_each_window(self, **kw) -> "DatasetPipeline":
        return self._chain(lambda ds: ds.random_shuffle(**kw))

    def repeat(self, times: int) -> "DatasetPipeline":
        return DatasetPipeline(list(self._window_fns) * times)

    def iter_rows(self) -> Iterator[Any]:
        for ds in self.iter_datasets():
            yield from ds.iter_rows()

    def iter_batches(self, **kw) -> Iterator[Any]:
        for ds in self.iter_datasets():
            yield from ds.iter_batches(**kw)

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def count(self) -> int:
        return sum(ds.count() for ds in self.iter_datasets())

    def split(self, n: int) -> List["DatasetPipeline"]:
        """Round-robin windows across n consumers (Train ingest)."""
        return [DatasetPipeline(self._window_fns[i::n])
                for i in range(n)]

    def __repr__(self):
        return f"DatasetPipeline(num_windows={self.num_windows()})"
