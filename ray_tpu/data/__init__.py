"""Distributed datasets on object-store blocks.

Capability mirror of the reference's `python/ray/data/` (SURVEY.md §2.3:
`Dataset` over plasma block refs, `BlockAccessor` per format, lazy-ish
transform pipeline, task-parallel compute, 2-stage shuffle, datasources,
windowed `DatasetPipeline`).  TPU-first notes: `iter_batches` yields
numpy-dict batches shaped for `jax.device_put` onto a mesh's data axis, and
`Dataset.split(n)` produces per-worker shards for Train ingest.
"""

from .block import Block, BlockAccessor, BlockMetadata  # noqa: F401
from .dataset import ActorPoolStrategy, DataIterator, Dataset  # noqa: F401
from .dataset_pipeline import DatasetPipeline  # noqa: F401
from .datasource import (  # noqa: F401
    BinaryDatasource,
    CSVDatasource,
    Datasource,
    FileBasedDatasource,
    JSONDatasource,
    NumpyDatasource,
    ImageDatasource,
    ParquetDatasource,
    RangeDatasource,
    ReadTask,
    TextDatasource,
)
from .grouped import GroupedData  # noqa: F401
from .plan import AllToAllStage, ExecutionPlan, OneToOneStage  # noqa: F401
from .read_api import (  # noqa: F401
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,  # noqa: A001  (mirrors the reference's public name)
    range_tensor,
    read_binary_files,
    read_csv,
    read_datasource,
    read_json,
    read_numpy,
    read_images,
    read_parquet,
    read_text,
    read_tfrecords,
)

from . import preprocessors  # noqa: F401,E402  (AIR preprocessor library)
