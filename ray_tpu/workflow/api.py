"""Workflow execution API (reference: `workflow/api.py:120,232,468` +
`workflow_executor.py:32`).

Steps are the DAG's FunctionNodes, identified by a deterministic
structural id; completed step results replay from storage on resume, so a
crashed workflow re-executes only unfinished steps.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional

from ..core.serialization import dumps_function, loads_function
from ..dag.node import ClassMethodNode, ClassNode, DAGNode, FunctionNode, \
    InputNode
from .storage import WorkflowStorage, get_base, list_workflow_ids, set_base

RUNNING = "RUNNING"
SUCCESSFUL = "SUCCESSFUL"
FAILED = "FAILED"


def init(storage_path: Optional[str] = None) -> None:
    if storage_path:
        set_base(storage_path)


def _assign_step_ids(node: DAGNode, counter: List[int],
                     ids: Dict[int, str]) -> None:
    """Post-order deterministic ids: stable across identical DAG builds."""
    if id(node) in ids:
        return
    children = []
    if isinstance(node, (FunctionNode, ClassMethodNode, ClassNode)):
        args = node._args
        kwargs = node._kwargs
        for v in list(args) + list(kwargs.values()):
            if isinstance(v, DAGNode):
                children.append(v)
    if isinstance(node, ClassMethodNode):
        children.append(node._class_node)
    for c in children:
        _assign_step_ids(c, counter, ids)
    name = getattr(getattr(node, "_fn", None), "_name", None) or \
        type(node).__name__
    ids[id(node)] = f"step_{counter[0]:04d}_{name}"
    counter[0] += 1


class Continuation:
    """A step's return value that CONTINUES the workflow with another
    DAG (reference: workflow/api.py:712 ``workflow.continuation`` —
    dynamic workflows: recursion/loops whose shape is decided at
    runtime).  The engine executes the inner DAG in the step's place,
    with inner step ids namespaced under the step so resume stays
    deterministic."""

    __slots__ = ("dag",)

    def __init__(self, dag: DAGNode):
        self.dag = dag


def continuation(dag_node: DAGNode) -> Continuation:
    if not isinstance(dag_node, DAGNode):
        raise TypeError(
            f"workflow.continuation expects a DAG node bind() result "
            f"(got {type(dag_node).__name__})")
    return Continuation(dag_node)


class _PendingContinuation:
    """Checkpoint marker: this step's own function already ran and
    returned a continuation — resume must NOT re-execute the function
    (its side effects happened), only finish the recorded chain."""

    __slots__ = ("dag_blob", "depth")

    def __init__(self, dag_blob: bytes, depth: int):
        self.dag_blob = dag_blob
        self.depth = depth


class _DurableExecutor:
    """Resolves the DAG like DAGNode.execute, but consults storage before
    running a FunctionNode and persists results after."""

    def __init__(self, storage: WorkflowStorage):
        self.storage = storage

    def execute(self, node: DAGNode) -> Any:
        from .. import api
        from ..core.driver import ObjectRef
        ids: Dict[int, str] = {}
        _assign_step_ids(node, [0], ids)
        cache: Dict[int, Any] = {}
        out = self._resolve(node, ids, cache)
        return api.get(out, timeout=600.0) \
            if isinstance(out, ObjectRef) else out

    def _run_continuations(self, step_id: str, val: Any,
                           depth: int = 0) -> Any:
        """Dynamic workflows: a returned continuation replaces the
        step's value with its inner DAG's result.  Each frontier is
        checkpointed as a _PendingContinuation BEFORE executing, so a
        crash mid-chain resumes from the deepest recorded frontier
        instead of re-running finished step functions; inner steps
        checkpoint under ids namespaced by step and depth.

        The namespace is a HASH of the parent id, not the id itself —
        literal nesting grows the path by one component per chain level
        and ENAMETOOLONGs somewhere around depth 150, wedging exactly
        the unbounded recursions continuations exist for.  Hashing
        keeps every id two path components deep at any depth, and stays
        deterministic across resume because the parent ids are."""
        import hashlib
        while isinstance(val, Continuation):
            self.storage.save_step(step_id, _PendingContinuation(
                dumps_function(val.dag), depth))
            tag = hashlib.sha1(step_id.encode()).hexdigest()[:12]
            sub_ids: Dict[int, str] = {}
            _assign_step_ids(val.dag, [0], sub_ids)
            prefix = f"cont_{tag}_c{depth}"
            sub_ids = {k: f"{prefix}/{v}" for k, v in sub_ids.items()}
            val = self._resolve(val.dag, sub_ids, {})
            depth += 1
        return val

    def _resolve(self, node: Any, ids, cache):
        from .. import api
        from ..core.driver import ObjectRef
        if not isinstance(node, DAGNode):
            return node
        if id(node) in cache:
            return cache[id(node)]
        step_id = ids.get(id(node))
        if isinstance(node, FunctionNode) and \
                self.storage.has_step(step_id):
            val = self.storage.load_step(step_id)
            if isinstance(val, _PendingContinuation):
                # the step function ran (side effects done); finish its
                # continuation chain from the recorded frontier
                val = self._run_continuations(
                    step_id,
                    Continuation(loads_function(val.dag_blob)),
                    depth=val.depth)
                self.storage.save_step(step_id, val)
            cache[id(node)] = val
            return val
        # resolve children then run
        if isinstance(node, (FunctionNode, ClassMethodNode, ClassNode)):
            args = [self._resolve(a, ids, cache) for a in node._args]
            kwargs = {k: self._resolve(v, ids, cache)
                      for k, v in node._kwargs.items()}
            if isinstance(node, FunctionNode):
                ref = node._fn.remote(*args, **kwargs)
                val = api.get(ref, timeout=600.0)
                val = self._run_continuations(step_id, val)
                self.storage.save_step(step_id, val)
            elif isinstance(node, ClassNode):
                val = node._cls.remote(*args, **kwargs)
            else:  # ClassMethodNode — actor state isn't durable
                handle = self._resolve(node._class_node, ids, cache)
                val = api.get(getattr(handle, node._method)
                              .remote(*args, **kwargs), timeout=600.0)
                self.storage.save_step(step_id, val)
            cache[id(node)] = val
            return val
        if isinstance(node, InputNode):
            return node._resolve(cache)
        raise TypeError(f"unsupported node {type(node)}")


def run(dag: DAGNode, *, workflow_id: Optional[str] = None) -> Any:
    """Execute durably; persists the DAG so `resume` can re-run it."""
    workflow_id = workflow_id or f"workflow_{uuid.uuid4().hex[:8]}"
    storage = WorkflowStorage(workflow_id)
    storage.save_dag(dumps_function(dag))
    storage.set_status(RUNNING)
    try:
        result = _DurableExecutor(storage).execute(dag)
    except BaseException:
        storage.set_status(FAILED)
        raise
    storage.save_output(result)
    storage.set_status(SUCCESSFUL)
    return result


def resume(workflow_id: str) -> Any:
    storage = WorkflowStorage(workflow_id)
    if storage.has_output():
        return storage.load_output()
    dag = loads_function(storage.load_dag())
    storage.set_status(RUNNING)
    try:
        result = _DurableExecutor(storage).execute(dag)
    except BaseException:
        storage.set_status(FAILED)
        raise
    storage.save_output(result)
    storage.set_status(SUCCESSFUL)
    return result


def resume_all() -> Dict[str, Any]:
    out = {}
    for wid in list_workflow_ids():
        st = WorkflowStorage(wid).get_status()
        if st in (RUNNING, FAILED):
            out[wid] = resume(wid)
    return out


def get_status(workflow_id: str) -> Optional[str]:
    return WorkflowStorage(workflow_id).get_status()


def get_output(workflow_id: str) -> Any:
    s = WorkflowStorage(workflow_id)
    if not s.has_output():
        raise ValueError(f"workflow {workflow_id} has no output yet")
    return s.load_output()


def list_all() -> List[tuple]:
    return [(wid, WorkflowStorage(wid).get_status())
            for wid in list_workflow_ids()]


def delete(workflow_id: str) -> None:
    import shutil
    shutil.rmtree(WorkflowStorage(workflow_id).root, ignore_errors=True)
