"""Durable workflows: DAG execution with persisted step results.

Capability mirror of the reference's `python/ray/workflow/`
(`workflow_executor.py:32`, `workflow_storage.py:229`, `api.py:120,232,468`
— run/resume/resume_all/list_all/get_status with step-level durability):
each step's result persists to storage on completion; resuming a crashed
workflow skips finished steps and re-executes the rest.
"""

from .api import (  # noqa: F401
    Continuation,
    continuation,
    delete,
    get_output,
    get_status,
    init,
    list_all,
    resume,
    resume_all,
    run,
)
from .events import (  # noqa: F401
    EventListener,
    KVEventListener,
    clear_event,
    trigger_event,
    wait_for_event,
)
from .storage import WorkflowStorage  # noqa: F401
