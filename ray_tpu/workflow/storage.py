"""Workflow persistence (reference: `workflow/workflow_storage.py:229`):
filesystem layout  <base>/<workflow_id>/{dag.pkl, status, steps/<id>.pkl}.
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any, Dict, List, Optional

_DEFAULT_BASE = None


def set_base(path: str) -> None:
    global _DEFAULT_BASE
    _DEFAULT_BASE = path
    os.makedirs(path, exist_ok=True)


def get_base() -> str:
    global _DEFAULT_BASE
    if _DEFAULT_BASE is None:
        _DEFAULT_BASE = os.path.join(tempfile.gettempdir(),
                                     "ray_tpu_workflows")
        os.makedirs(_DEFAULT_BASE, exist_ok=True)
    return _DEFAULT_BASE


class WorkflowStorage:
    def __init__(self, workflow_id: str, base: Optional[str] = None):
        self.workflow_id = workflow_id
        self.root = os.path.join(base or get_base(), workflow_id)
        os.makedirs(os.path.join(self.root, "steps"), exist_ok=True)

    # -- atomic file io -----------------------------------------------------
    def _write(self, path: str, obj: Any) -> None:
        # continuation step ids are hierarchical (step/c0/step...):
        # the parent directories exist only once the chain runs
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(obj, f)
        os.replace(tmp, path)

    def _read(self, path: str) -> Any:
        with open(path, "rb") as f:
            return pickle.load(f)

    # -- dag ----------------------------------------------------------------
    def save_dag(self, dag_blob: bytes) -> None:
        self._write(os.path.join(self.root, "dag.pkl"), dag_blob)

    def load_dag(self) -> bytes:
        return self._read(os.path.join(self.root, "dag.pkl"))

    # -- status -------------------------------------------------------------
    def set_status(self, status: str) -> None:
        self._write(os.path.join(self.root, "status"), status)

    def get_status(self) -> Optional[str]:
        p = os.path.join(self.root, "status")
        return self._read(p) if os.path.exists(p) else None

    # -- steps --------------------------------------------------------------
    def _step_path(self, step_id: str) -> str:
        return os.path.join(self.root, "steps", f"{step_id}.pkl")

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self._step_path(step_id))

    def save_step(self, step_id: str, result: Any) -> None:
        self._write(self._step_path(step_id), result)

    def load_step(self, step_id: str) -> Any:
        return self._read(self._step_path(step_id))

    def list_steps(self) -> List[str]:
        """All step ids, INCLUDING hierarchical continuation
        checkpoints (steps/<id>/c0/<id>.pkl → '<id>/c0/<id>')."""
        d = os.path.join(self.root, "steps")
        out = []
        for root, _, files in os.walk(d):
            rel = os.path.relpath(root, d)
            for f in files:
                if f.endswith(".pkl"):
                    sid = f[:-4] if rel == "." else f"{rel}/{f[:-4]}"
                    out.append(sid)
        return sorted(out)

    # -- output -------------------------------------------------------------
    def save_output(self, value: Any) -> None:
        self._write(os.path.join(self.root, "output.pkl"), value)

    def load_output(self) -> Any:
        return self._read(os.path.join(self.root, "output.pkl"))

    def has_output(self) -> bool:
        return os.path.exists(os.path.join(self.root, "output.pkl"))


def list_workflow_ids(base: Optional[str] = None) -> List[str]:
    b = base or get_base()
    return sorted(d for d in os.listdir(b)
                  if os.path.isdir(os.path.join(b, d)))
