"""Workflow events: durable steps that wait for external signals.

Capability mirror of the reference's workflow event system
(`workflow/event_listener.py` EventListener ABC + HTTP event provider,
`workflow/api.py wait_for_event`): a workflow step can block until an
external event fires, and because the step's result (the event payload)
persists like any other step, a resumed workflow replays the payload
instead of waiting again.

The built-in provider signals through the controller KV (namespace
``wf_events``): any driver/task calls :func:`trigger_event`, and the
dashboard head exposes ``POST /api/workflow_events/<name>`` (the
HTTP-provider role) so external systems can fire events with a plain
HTTP call.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import cloudpickle

_NS = "wf_events"


class EventListener:
    """Poll-based listener ABC (reference: workflow EventListener).

    Subclasses implement :meth:`poll`, returning ``None`` while the
    event has not fired and the payload (any picklable value; ``None``
    payloads are represented by returning ``(True, None)`` from
    :meth:`poll_with_flag`) once it has.
    """

    def poll(self) -> Optional[Any]:
        raise NotImplementedError

    def poll_with_flag(self) -> tuple:
        """→ (fired, payload); override when None is a valid payload."""
        payload = self.poll()
        return (payload is not None), payload


class KVEventListener(EventListener):
    """Event signaled via the controller KV (cluster-wide, durable for
    the controller's lifetime + snapshots)."""

    def __init__(self, name: str):
        self.name = name

    def poll_with_flag(self) -> tuple:
        from ..api import _ensure_initialized
        core = _ensure_initialized()
        raw = core.controller.call(
            "kv_get", {"ns": _NS, "key": self.name.encode()})
        if not raw:
            return False, None
        return True, cloudpickle.loads(raw)

    def poll(self) -> Optional[Any]:
        fired, payload = self.poll_with_flag()
        return payload if fired else None


def trigger_event(name: str, payload: Any = None) -> None:
    """Fire an event: every workflow step waiting on ``name`` unblocks
    with ``payload``."""
    from ..api import _ensure_initialized
    core = _ensure_initialized()
    core.controller.call("kv_put", {
        "ns": _NS, "key": name.encode(),
        "value": cloudpickle.dumps(payload)})


def clear_event(name: str) -> None:
    from ..api import _ensure_initialized
    core = _ensure_initialized()
    core.controller.call("kv_del", {"ns": _NS, "key": name.encode()})


def wait_for_event(listener: Any, *, poll_interval_s: float = 0.2,
                   timeout_s: Optional[float] = None):
    """A DAG node that completes when the event fires, yielding its
    payload.  ``listener`` is an :class:`EventListener` instance or a
    plain string (KV event name).  Durable: the payload persists as the
    step's result, so resume replays it without re-waiting."""
    from .. import api

    if isinstance(listener, str):
        listener = KVEventListener(listener)

    @api.remote
    def _wait_for_event_step(pickled_listener: bytes,
                             interval: float,
                             timeout: Optional[float]):
        lst = cloudpickle.loads(pickled_listener)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            fired, payload = lst.poll_with_flag()
            if fired:
                return payload
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"event did not fire within {timeout}s")
            time.sleep(interval)

    return _wait_for_event_step.bind(cloudpickle.dumps(listener),
                                     poll_interval_s, timeout_s)
