"""Decoder-only transformer (GPT-2 and Llama families), TPU-first.

Design (idiomatic JAX, not a torch translation):

  * parameters are a plain pytree of jnp arrays; alongside it a matching
    ``params_axes`` tree of *logical axis* tuples feeds the sharding engine
    (`ray_tpu.parallel.sharding`) — TP/FSDP/PP are rules-table changes.
  * the layer stack is ONE set of stacked weights scanned with ``lax.scan``
    (fast compile, natural pipeline-parallel partitioning over the leading
    "layers" axis), with optional per-layer ``jax.checkpoint`` remat.
  * attention dispatches to the Pallas flash kernel on TPU
    (`ray_tpu.ops.attention`), with sequence-parallel ring attention as a
    config switch.
  * compute dtype bf16, params and softmax/norm statistics fp32 — the MXU
    recipe.

Configs: ``TransformerConfig.gpt2()`` (learned positions, GELU, LayerNorm)
and ``TransformerConfig.llama()`` (RoPE, SwiGLU, RMSNorm, GQA).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.attention import multi_head_attention
from ..ops.norms import layernorm, rmsnorm
from ..ops.rotary import apply_rotary, rotary_angles

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50304          # GPT-2 vocab padded to a 128 multiple
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    n_kv_heads: Optional[int] = None  # None → MHA
    d_ff: Optional[int] = None        # None → 4*d_model (gelu) / 8/3 (swiglu)
    max_seq_len: int = 1024
    pos_emb: str = "learned"          # "learned" | "rope"
    activation: str = "gelu"          # "gelu" | "swiglu"
    norm: str = "layernorm"           # "layernorm" | "rmsnorm"
    tie_embeddings: bool = True
    rope_base: float = 10000.0
    dtype: Any = jnp.bfloat16         # activation/compute dtype
    param_dtype: Any = jnp.float32
    attention_impl: str = "auto"      # "auto"|"flash"|"reference"|"ring"
    causal: bool = True               # False → bidirectional (encoders)
    remat: Any = True                 # False | True (full) | "dots":
    #   "dots" saves matmul outputs and recomputes only elementwise ops in
    #   the backward pass — most of full remat's memory win at zero extra
    #   MXU work (matmuls are never recomputed).  On one v5e chip this is
    #   what lets gpt2-small train at batch 32 instead of 8.
    embed_impl: str = "gather"        # "gather" | "one_hot" (MXU-matmul
    #   embedding: gather-bwd is a serialized scatter-add on TPU)
    norm_remat: bool = False          # recompute layernorm/rmsnorm in bwd
    #   instead of saving their fp32 intermediates — on v5e those saves
    #   ([b, s, d] fp32 x 2 per layer) are what keep gpt2-small from
    #   fitting batch 16 without full remat
    loss_chunk: int = 0               # >0 → chunked cross entropy: logits
    #   materialize [b, chunk, vocab] at a time (rematerialized in bwd)
    #   instead of the full [b, s, vocab] fp32 tensor — the biggest HBM
    #   spike of LM training at GPT-2 vocab sizes
    # -- pipeline parallelism (SURVEY §2.4 row 3; parallel/pipeline.py) -----
    pp_stages: int = 1                # >1 → GPipe schedule over mesh "pp"
    pp_microbatches: Optional[int] = None  # None → pp_stages
    # -- mixture of experts (SURVEY §2.4 row 5; ops/moe.py) -----------------
    n_experts: int = 0                # 0 → dense FFN
    expert_top_k: int = 2
    capacity_factor: float = 2.0
    router_aux_weight: float = 0.01   # Switch load-balancing loss weight

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def ff_dim(self) -> int:
        if self.d_ff is not None:
            return self.d_ff
        if self.activation == "swiglu":
            # Llama convention: 8/3 * d, rounded up to a 256 multiple
            return ((int(8 * self.d_model / 3) + 255) // 256) * 256
        return 4 * self.d_model

    # -- presets (sizes follow the public GPT-2/Llama papers) ---------------
    @staticmethod
    def gpt2(size: str = "small", **kw) -> "TransformerConfig":
        dims = {"small": (768, 12, 12), "medium": (1024, 24, 16),
                "large": (1280, 36, 20), "xl": (1600, 48, 25)}[size]
        d, l, h = dims
        return TransformerConfig(
            vocab_size=50304, d_model=d, n_layers=l, n_heads=h,
            max_seq_len=kw.pop("max_seq_len", 1024), pos_emb="learned",
            activation="gelu", norm="layernorm", tie_embeddings=True, **kw)

    @staticmethod
    def llama(size: str = "1b", **kw) -> "TransformerConfig":
        dims = {  # d_model, layers, heads, kv_heads, d_ff, vocab
            "tiny": (512, 4, 8, 4, 1408, 32000),
            "1b": (2048, 16, 32, 8, 8192, 128256),
            "3b": (3072, 28, 24, 8, 8192, 128256),
            "8b": (4096, 32, 32, 8, 14336, 128256),
        }[size]
        d, l, h, hk, ff, v = dims
        return TransformerConfig(
            vocab_size=v, d_model=d, n_layers=l, n_heads=h, n_kv_heads=hk,
            d_ff=ff, max_seq_len=kw.pop("max_seq_len", 4096),
            pos_emb="rope", activation="swiglu", norm="rmsnorm",
            tie_embeddings=False, **kw)

    @staticmethod
    def tiny(**kw) -> "TransformerConfig":
        """Test-sized model that still exercises every code path."""
        defaults = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, max_seq_len=128, pos_emb="rope",
                        activation="swiglu", norm="rmsnorm",
                        tie_embeddings=False, remat=False)
        defaults.update(kw)
        return TransformerConfig(**defaults)


def _per_layer_matmul_params(cfg: TransformerConfig, active: bool) -> int:
    """Matmul parameters per layer; for MoE, ``active`` counts only the
    top-k experts a token actually visits (the FLOP count), while
    ``active=False`` counts every expert (the memory count)."""
    d, ff, hd = cfg.d_model, cfg.ff_dim, cfg.head_dim
    attn = d * cfg.n_heads * hd + 2 * d * cfg.kv_heads * hd \
        + cfg.n_heads * hd * d
    base_mlp = d * ff * (3 if cfg.activation == "swiglu" else 2)
    if cfg.n_experts:
        mult = cfg.expert_top_k if active else cfg.n_experts
        mlp = mult * base_mlp + d * cfg.n_experts  # + router
    else:
        mlp = base_mlp
    return attn + mlp


def count_params(cfg: TransformerConfig) -> int:
    d = cfg.d_model
    norms = 2 * d * (2 if cfg.norm == "layernorm" else 1)
    per_layer = _per_layer_matmul_params(cfg, active=False) + norms
    emb = cfg.vocab_size * d
    if cfg.pos_emb == "learned":
        emb += cfg.max_seq_len * d
    head = 0 if cfg.tie_embeddings else cfg.vocab_size * d
    final = d * (2 if cfg.norm == "layernorm" else 1)
    return cfg.n_layers * per_layer + emb + head + final


def flops_per_token(cfg: TransformerConfig, seq_len: int) -> float:
    """Training FLOPs/token: 6*N_active_matmul + causal attention term."""
    d = cfg.d_model
    unembed = cfg.vocab_size * d  # tied or not, the logits matmul runs
    n_matmul = cfg.n_layers * _per_layer_matmul_params(cfg, active=True) \
        + unembed
    # qk+pv over the visible window: half the positions when causal,
    # all of them for bidirectional encoders (causal=False)
    attn_factor = 6 if cfg.causal else 12
    attn = attn_factor * cfg.n_layers * cfg.n_heads * cfg.head_dim \
        * seq_len
    return 6 * n_matmul + attn


def decode_flops_per_token(cfg: TransformerConfig,
                           context_len: int) -> float:
    """Inference forward FLOPs for ONE token at cache position
    ``context_len``: 2*N_active_matmul for the weight matmuls (forward
    only — no backward factor) plus the attention reads against the KV
    cache (qk^T and probs·v, 2 FLOPs per MAC each, over every cached
    position)."""
    n_matmul = cfg.n_layers * _per_layer_matmul_params(cfg, active=True) \
        + cfg.vocab_size * cfg.d_model   # unembed logits matmul
    attn = 4 * cfg.n_layers * cfg.n_heads * cfg.head_dim * context_len
    return 2 * n_matmul + attn


def engine_flops_table(cfg: TransformerConfig, max_len: int,
                       draft_cfg: "TransformerConfig" = None) -> dict:
    """Analytic FLOPs-per-token for each of the serve engine's jitted
    programs (the dispatch profiler's MFU numerators), evaluated at the
    mid-stream cache position ``max_len // 2`` — the average context a
    token attends over a full stream.  Pure-copy programs (cache
    insert/gather) are 0: they move bytes, not FLOPs, and the profiler
    reports no MFU for them."""
    mid = max(1, max_len // 2)
    target = decode_flops_per_token(cfg, mid)
    table = {
        "decode_step": target,
        "prefill_chunk": target,   # per prompt token, same forward
        "verify": target,          # k+1-wide target forward per token
        "cache_insert": 0.0,
        "prefix_gather": 0.0,
    }
    if draft_cfg is not None:
        draft = decode_flops_per_token(draft_cfg, mid)
        table["draft_propose"] = draft
        table["draft_prefill_chunk"] = draft
    return table


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def init_params(key: jax.Array, cfg: TransformerConfig
                ) -> Tuple[Params, Params]:
    """Returns (params, params_axes): matching pytrees of weights and
    logical-axis tuples.  Stacked layer weights carry a leading "layers"
    axis (pipeline-shardable)."""
    d, hd, h, hk, ff, L = (cfg.d_model, cfg.head_dim, cfg.n_heads,
                           cfg.kv_heads, cfg.ff_dim, cfg.n_layers)
    pt = cfg.param_dtype
    keys = iter(jax.random.split(key, 16))

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, pt) / math.sqrt(fan_in))

    def stack(k, shape, fan_in):
        return dense(k, (L,) + shape, fan_in)

    params: Params = {
        "embed": {"tok": jax.random.normal(next(keys), (cfg.vocab_size, d),
                                           pt) * 0.02},
        "layers": {
            "attn_norm": jnp.ones((L, d), pt),
            "wq": stack(next(keys), (d, h, hd), d),
            "wk": stack(next(keys), (d, hk, hd), d),
            "wv": stack(next(keys), (d, hk, hd), d),
            "wo": stack(next(keys), (h, hd, d), h * hd),
            "mlp_norm": jnp.ones((L, d), pt),
        },
        "final_norm": jnp.ones((d,), pt),
    }
    axes: Params = {
        "embed": {"tok": ("vocab", "embed")},
        "layers": {
            "attn_norm": ("layers", "embed"),
            "wq": ("layers", "embed", "heads", "kv"),
            "wk": ("layers", "embed", "heads", "kv"),
            "wv": ("layers", "embed", "heads", "kv"),
            "wo": ("layers", "heads", "kv", "embed"),
            "mlp_norm": ("layers", "embed"),
        },
        "final_norm": ("embed",),
    }
    if cfg.n_experts:
        E = cfg.n_experts
        params["layers"]["router"] = stack(next(keys), (d, E), d)
        axes["layers"]["router"] = ("layers", "embed", "expert")
        params["layers"]["w_in"] = stack(next(keys), (E, d, ff), d)
        axes["layers"]["w_in"] = ("layers", "expert", "embed", "mlp")
        params["layers"]["w_out"] = stack(next(keys), (E, ff, d), ff)
        axes["layers"]["w_out"] = ("layers", "expert", "mlp", "embed")
        if cfg.activation == "swiglu":
            params["layers"]["w_gate"] = stack(next(keys), (E, d, ff), d)
            axes["layers"]["w_gate"] = ("layers", "expert", "embed", "mlp")
    else:
        params["layers"]["w_in"] = stack(next(keys), (d, ff), d)
        axes["layers"]["w_in"] = ("layers", "embed", "mlp")
        params["layers"]["w_out"] = stack(next(keys), (ff, d), ff)
        axes["layers"]["w_out"] = ("layers", "mlp", "embed")
        if cfg.activation == "swiglu":
            params["layers"]["w_gate"] = stack(next(keys), (d, ff), d)
            axes["layers"]["w_gate"] = ("layers", "embed", "mlp")
    if cfg.norm == "layernorm":
        params["layers"]["attn_norm_b"] = jnp.zeros((L, d), pt)
        params["layers"]["mlp_norm_b"] = jnp.zeros((L, d), pt)
        params["final_norm_b"] = jnp.zeros((d,), pt)
        axes["layers"]["attn_norm_b"] = ("layers", "embed")
        axes["layers"]["mlp_norm_b"] = ("layers", "embed")
        axes["final_norm_b"] = ("embed",)
    if cfg.pos_emb == "learned":
        params["embed"]["pos"] = jax.random.normal(
            next(keys), (cfg.max_seq_len, d), pt) * 0.01
        axes["embed"]["pos"] = (None, "embed")
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(next(keys), (d, cfg.vocab_size), d)
        axes["lm_head"] = ("embed", "vocab")
    return params, axes


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def remat_policy(remat):
    """Resolve a config's ``remat`` field to a jax.checkpoint policy, or
    None when remat is off.  Shared by every model family (transformer,
    ViT) so the accepted values can't diverge."""
    if not remat:
        return None
    if remat == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if remat is True:
        return jax.checkpoint_policies.nothing_saveable
    # an unrecognized string must not silently mean full remat
    raise ValueError(f"remat={remat!r}: expected False, True, or 'dots'")


def _norm(cfg, x, scale, bias):
    if cfg.norm == "rmsnorm":
        return rmsnorm(x, scale)
    return layernorm(x, scale, bias)


def _layer(cfg: TransformerConfig, x: jnp.ndarray, lp: Params,
           cos, sin) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One transformer block; returns (x, router_aux_loss)."""
    b, s, d = x.shape
    h, hk, hd = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    dt = cfg.dtype

    norm = functools.partial(_norm, cfg)
    if cfg.norm_remat:
        norm = jax.checkpoint(
            norm, policy=jax.checkpoint_policies.nothing_saveable)

    y = norm(x, lp["attn_norm"], lp.get("attn_norm_b"))
    q = jnp.einsum("bsd,dhk->bshk", y, lp["wq"].astype(dt))
    k = jnp.einsum("bsd,dhk->bshk", y, lp["wk"].astype(dt))
    v = jnp.einsum("bsd,dhk->bshk", y, lp["wv"].astype(dt))
    if cfg.pos_emb == "rope":
        q = apply_rotary(q, cos, sin)
        k = apply_rotary(k, cos, sin)
    attn = multi_head_attention(q, k, v, causal=cfg.causal,
                                impl=cfg.attention_impl)
    x = x + jnp.einsum("bshk,hkd->bsd", attn, lp["wo"].astype(dt))

    y = norm(x, lp["mlp_norm"], lp.get("mlp_norm_b"))
    z, aux = _ffn(cfg, y, lp)
    return x + z, aux


def _ffn(cfg: TransformerConfig, y: jnp.ndarray, lp: Params
         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Post-attention FFN on a normed input — ONE implementation shared
    by training/prefill (`_layer`) and KV-cache decode
    (`models/generate.py`), so the architectures can't desynchronize.
    → (residual delta, router aux loss)."""
    dt = cfg.dtype
    aux = jnp.zeros((), jnp.float32)
    if cfg.n_experts:
        from ..ops.moe import moe_ffn
        z, aux = moe_ffn(
            y, lp["router"], lp["w_in"], lp["w_out"], lp.get("w_gate"),
            top_k=cfg.expert_top_k, capacity_factor=cfg.capacity_factor)
        return z, aux
    if cfg.activation == "swiglu":
        up = jnp.einsum("bsd,df->bsf", y, lp["w_in"].astype(dt))
        gate = jnp.einsum("bsd,df->bsf", y, lp["w_gate"].astype(dt))
        z = jax.nn.silu(gate) * up
    else:
        z = jax.nn.gelu(jnp.einsum("bsd,df->bsf", y, lp["w_in"].astype(dt)))
    return jnp.einsum("bsf,fd->bsd", z, lp["w_out"].astype(dt)), aux


def _trunk(params: Params, tokens: jnp.ndarray, cfg: TransformerConfig
           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Everything up to (and including) the final norm:
    tokens [b, s] → (hidden [b, s, d] in cfg.dtype, mean router aux)."""
    b, s = tokens.shape
    dt = cfg.dtype
    if cfg.embed_impl == "one_hot":
        # gather's backward is a scatter-add into [vocab, d] — serialized
        # and slow on TPU; the one-hot formulation turns fwd AND bwd into
        # MXU matmuls.  Chunked over tokens so the one-hot buffer peaks
        # at [chunk, vocab] (~100 MB bf16 at vocab 50k) instead of
        # [b*s, vocab] (~820 MB at b8/s1024) — XLA may fuse it away, but
        # the bound must not depend on that.
        emb = params["embed"]["tok"].astype(dt)
        flat = tokens.reshape(-1)
        chunk = 1024
        if flat.size <= chunk:
            x = jax.nn.one_hot(flat, cfg.vocab_size, dtype=dt) @ emb
        else:
            pad = (-flat.size) % chunk
            chunks = jnp.pad(flat, (0, pad)).reshape(-1, chunk)
            x = jax.lax.map(
                lambda t: jax.nn.one_hot(t, cfg.vocab_size, dtype=dt)
                @ emb, chunks).reshape(-1, cfg.d_model)[:flat.size]
        x = x.reshape(b, s, cfg.d_model)
    elif cfg.embed_impl == "gather":
        x = params["embed"]["tok"][tokens].astype(dt)
    else:  # a typo must not silently mean the gather path (cf. remat_policy)
        raise ValueError(f"embed_impl={cfg.embed_impl!r}: expected "
                         f"'gather' or 'one_hot'")
    if cfg.pos_emb == "learned":
        x = x + params["embed"]["pos"][:s].astype(dt)
    cos, sin = (rotary_angles(s, cfg.head_dim, cfg.rope_base)
                if cfg.pos_emb == "rope" else (None, None))

    layer = functools.partial(_layer, cfg)
    policy = remat_policy(cfg.remat)
    if policy is not None:
        layer = jax.checkpoint(layer, static_argnums=(), policy=policy)

    def body(carry, lp):
        h, aux = carry
        h, aux_l = layer(h, lp, cos, sin)
        return (h, aux + aux_l), None

    if cfg.pp_stages > 1:
        from ..parallel.pipeline import (microbatch, pipeline_apply,
                                         unmicrobatch)
        if cfg.n_layers % cfg.pp_stages:
            raise ValueError(f"{cfg.n_layers} layers not divisible by "
                             f"{cfg.pp_stages} pipeline stages")
        n_micro = cfg.pp_microbatches or cfg.pp_stages

        def stage_fn(slab, state):
            out, _ = jax.lax.scan(body, state, slab)
            return out

        x_mb = (microbatch(x, n_micro),
                jnp.zeros((n_micro,), jnp.float32))
        h_mb, aux_mb = pipeline_apply(
            stage_fn, params["layers"], x_mb,
            n_stages=cfg.pp_stages, n_micro=n_micro)
        x = unmicrobatch(h_mb)
        aux = aux_mb.sum() / (n_micro * cfg.n_layers)
    else:
        (x, aux), _ = jax.lax.scan(
            body, (x, jnp.zeros((), jnp.float32)), params["layers"])
        aux = aux / cfg.n_layers
    x = _norm(cfg, x, params["final_norm"], params.get("final_norm_b"))
    return x, aux


def _unembed(params: Params, cfg: TransformerConfig) -> jnp.ndarray:
    w = (params["embed"]["tok"].T if cfg.tie_embeddings
         else params["lm_head"])
    return w.astype(cfg.dtype)


def forward_with_aux(params: Params, tokens: jnp.ndarray,
                     cfg: TransformerConfig
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """tokens [batch, seq] int32 → (logits [batch, seq, vocab] fp32,
    mean router aux loss).  With ``cfg.pp_stages > 1`` the layer stack runs
    as a GPipe pipeline over the ambient mesh's ``pp`` axis
    (parallel/pipeline.py); otherwise a plain `lax.scan`."""
    x, aux = _trunk(params, tokens, cfg)
    # fp32 MXU accumulation straight out of the dot — rounding the logits
    # through bf16 first would cost ~3 decimal digits on a 50k-way softmax
    logits = jnp.einsum("bsd,dv->bsv", x, _unembed(params, cfg),
                        preferred_element_type=jnp.float32)
    return logits, aux


def forward(params: Params, tokens: jnp.ndarray,
            cfg: TransformerConfig) -> jnp.ndarray:
    """tokens [batch, seq] int32 → logits [batch, seq, vocab] fp32."""
    return forward_with_aux(params, tokens, cfg)[0]


def lm_loss(params: Params, batch: Dict[str, jnp.ndarray],
            cfg: TransformerConfig) -> jnp.ndarray:
    """Next-token cross entropy.  ``batch`` has "tokens" [b, s]; loss is on
    positions 0..s-2 predicting 1..s-1.

    With ``cfg.loss_chunk`` set (and dividing s), the unembed + softmax
    runs chunk-by-chunk under `jax.checkpoint`, so only one
    [b, chunk, vocab] logits block exists at a time (forward AND
    backward) instead of the full [b, s, vocab] fp32 tensor.
    """
    import optax

    # run the model on the FULL sequence and shift the logits: keeps the
    # model's seq length divisible by sequence-parallel mesh axes (sp)
    tokens = batch["tokens"]
    b, s = tokens.shape
    aux_weight = cfg.router_aux_weight if cfg.n_experts else 0.0
    mask = batch.get("mask")

    if cfg.loss_chunk and s % cfg.loss_chunk:
        # falling back silently would re-materialize the full
        # [b, s, vocab] logits — the OOM cliff loss_chunk exists to avoid
        raise ValueError(f"seq length {s} is not divisible by "
                         f"loss_chunk={cfg.loss_chunk}")
    if cfg.loss_chunk:
        x, aux = _trunk(params, tokens, cfg)
        w_out = _unembed(params, cfg)
        # target for the LAST position is a dummy masked to weight 0
        targets = jnp.concatenate(
            [tokens[:, 1:], jnp.zeros((b, 1), tokens.dtype)], axis=1)
        valid = jnp.concatenate(
            [jnp.ones((b, s - 1), jnp.float32),
             jnp.zeros((b, 1), jnp.float32)], axis=1)
        if mask is not None:
            shifted = jnp.concatenate(
                [mask[:, 1:], jnp.zeros((b, 1), mask.dtype)], axis=1)
            valid = valid * shifted.astype(jnp.float32)
        n = s // cfg.loss_chunk
        xc = jnp.swapaxes(x.reshape(b, n, cfg.loss_chunk, -1), 0, 1)
        tc = jnp.swapaxes(targets.reshape(b, n, cfg.loss_chunk), 0, 1)
        vc = jnp.swapaxes(valid.reshape(b, n, cfg.loss_chunk), 0, 1)

        def chunk_sum(xi, ti, vi):
            logits = jnp.einsum("bcd,dv->bcv", xi, w_out,
                                preferred_element_type=jnp.float32)
            ls = optax.softmax_cross_entropy_with_integer_labels(logits, ti)
            return (ls * vi).sum()

        def body(acc, inp):
            xi, ti, vi = inp
            return acc + jax.checkpoint(chunk_sum)(xi, ti, vi), None

        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                (xc, tc, vc))
        return total / jnp.maximum(valid.sum(), 1.0) + aux_weight * aux

    logits, aux = forward_with_aux(params, tokens, cfg)
    logits = logits[:, :-1]
    targets = tokens[:, 1:]
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    aux_term = aux_weight * aux
    if mask is not None:
        m = mask[:, 1:].astype(jnp.float32)
        return (losses * m).sum() / jnp.maximum(m.sum(), 1.0) + aux_term
    return losses.mean() + aux_term


def make_train_step(cfg: TransformerConfig, optimizer, accum_steps: int = 1):
    """(params, opt_state, batch) → (params, opt_state, metrics); pure, jit
    it under any mesh/sharding.

    ``accum_steps > 1`` runs gradient accumulation INSIDE the compiled
    step: the batch is split into ``accum_steps`` microbatches scanned
    with a summed f32 grad carry, and the optimizer applies once.  Two
    uses: (a) effective batches beyond HBM (activation memory scales
    with the microbatch), and (b) on memory-bound chips the Adam-moment
    read/write traffic amortizes over ``accum_steps`` × more tokens —
    measured on the v5e as the difference between gpt2-medium's
    batch-bound 0.3865 MFU and the accumulated operating point
    (TPU_PROBE15_r05.jsonl)."""

    def grad_fn(params, batch):
        return jax.value_and_grad(
            functools.partial(lm_loss, cfg=cfg))(params, batch)

    def step(params, opt_state, batch):
        import optax

        if accum_steps > 1:
            full = batch["tokens"].shape[0]
            if full % accum_steps:
                raise ValueError(
                    f"batch {full} not divisible by "
                    f"accum_steps {accum_steps}")
            micro = full // accum_steps
            # split EVERY batch leaf (tokens, mask, ...) on the batch
            # axis so the microbatch loss sees the same keys the flat
            # path does
            mbatch = jax.tree_util.tree_map(
                lambda v: v.reshape((accum_steps, micro) + v.shape[1:]),
                batch)

            def micro_step(carry, mb):
                gsum, lsum, csum = carry
                loss, grads = grad_fn(params, mb)
                # weight by this microbatch's valid-token count so the
                # combined gradient equals the FULL-batch step even when
                # a padding mask is uneven across microbatches (lm_loss
                # normalizes per call by its own mask[:, 1:].sum();
                # equal 1/accum weighting would over-weight nearly-empty
                # microbatches)
                if "mask" in mb:
                    count = mb["mask"][:, 1:].astype(jnp.float32).sum()
                else:
                    count = jnp.float32(micro
                                        * (mb["tokens"].shape[1] - 1))
                gsum = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32) * count,
                    gsum, grads)
                return (gsum, lsum + loss * count, csum + count), None

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            (gsum, lsum, csum), _ = jax.lax.scan(
                micro_step, (zeros, jnp.float32(0.0), jnp.float32(0.0)),
                mbatch)
            csum = jnp.maximum(csum, 1.0)
            # back to the dtype grad_fn itself produces (param dtype) so
            # optimizer state dtypes — and therefore buffer donation —
            # match the accum_steps=1 path
            grads = jax.tree_util.tree_map(
                lambda g, p: (g / csum).astype(p.dtype), gsum, params)
            loss = lsum / csum
        else:
            loss, grads = grad_fn(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                             for g in jax.tree_util.tree_leaves(grads)))
        return params, opt_state, {"loss": loss, "grad_norm": gnorm}

    return step
