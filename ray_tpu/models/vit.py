"""ViT: vision transformer classification family.

The encoder-side model family complementing the decoder LMs in
`transformer.py` (an original addition — the reference framework ships
no model zoo; its vision path is the RLlib catalog's CNN).  TPU-first
like the LM trunk: patchify is a reshape + one matmul (MXU-friendly,
no gather), the encoder reuses the SAME `_layer` blocks (scan over
stacked weights, optional remat, flash/reference attention with
``causal=False``), and every parameter carries logical axes so
`parallel.pytree_shardings` shards it over dp/fsdp/tp meshes
unchanged.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .transformer import (TransformerConfig, _layer, _norm, init_params,
                          remat_policy)

Params = Any


@dataclasses.dataclass
class ViTConfig:
    image_size: int = 32
    patch_size: int = 4
    channels: int = 3
    num_classes: int = 10
    d_model: int = 192
    n_layers: int = 6
    n_heads: int = 4
    d_ff: Optional[int] = None
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    attention_impl: str = "auto"

    @property
    def n_patches(self) -> int:
        side = self.image_size // self.patch_size
        return side * side

    @property
    def seq_len(self) -> int:
        return self.n_patches + 1          # +1 for the CLS token

    def block_cfg(self) -> TransformerConfig:
        """The encoder blocks are plain transformer layers with
        bidirectional attention — one shared implementation."""
        return TransformerConfig(
            vocab_size=8,                   # unused (embed is replaced)
            d_model=self.d_model, n_layers=self.n_layers,
            n_heads=self.n_heads, d_ff=self.d_ff,
            max_seq_len=self.seq_len, pos_emb="learned",
            activation="gelu", norm="layernorm", causal=False,
            dtype=self.dtype, param_dtype=self.param_dtype,
            remat=self.remat, attention_impl=self.attention_impl)

    @staticmethod
    def tiny(**kw) -> "ViTConfig":
        defaults = dict(image_size=16, patch_size=4, channels=1,
                        num_classes=4, d_model=64, n_layers=2,
                        n_heads=4)
        defaults.update(kw)
        return ViTConfig(**defaults)

    @staticmethod
    def base(**kw) -> "ViTConfig":
        """ViT-B/16 dimensions (public paper sizes)."""
        defaults = dict(image_size=224, patch_size=16, channels=3,
                        num_classes=1000, d_model=768, n_layers=12,
                        n_heads=12)
        defaults.update(kw)
        return ViTConfig(**defaults)


def init_vit_params(key: jax.Array, cfg: ViTConfig
                    ) -> Tuple[Params, Params]:
    """(params, logical axes).  Encoder layers come from the shared
    transformer initializer; embed/head are vision-specific."""
    kb, kp, kc, kpos, kh = jax.random.split(key, 5)
    base, base_axes = init_params(kb, cfg.block_cfg())
    pt = cfg.param_dtype
    d = cfg.d_model
    patch_dim = cfg.patch_size * cfg.patch_size * cfg.channels
    params: Params = {
        "layers": base["layers"],
        "final_norm": base["final_norm"],
        "final_norm_b": base["final_norm_b"],
        "patch": {
            "w": jax.random.normal(kp, (patch_dim, d), pt)
            / math.sqrt(patch_dim),
            "b": jnp.zeros((d,), pt),
        },
        "cls": jax.random.normal(kc, (1, 1, d), pt) * 0.02,
        "pos": jax.random.normal(kpos, (cfg.seq_len, d), pt) * 0.02,
        "head": {
            "w": jax.random.normal(kh, (d, cfg.num_classes), pt)
            / math.sqrt(d),
            "b": jnp.zeros((cfg.num_classes,), pt),
        },
    }
    axes: Params = {
        "layers": base_axes["layers"],
        "final_norm": base_axes["final_norm"],
        "final_norm_b": base_axes["final_norm_b"],
        "patch": {"w": (None, "embed"), "b": ("embed",)},
        "cls": (None, None, "embed"),
        "pos": (None, "embed"),
        "head": {"w": ("embed", "vocab"), "b": ("vocab",)},
    }
    return params, axes


def patchify(images: jnp.ndarray, cfg: ViTConfig) -> jnp.ndarray:
    """[b, H, W, C] → [b, n_patches, P*P*C] by pure reshape/transpose —
    no gather, no conv lowering surprises; the single following matmul
    is the whole embedding."""
    expect = (cfg.image_size, cfg.image_size, cfg.channels)
    if images.shape[1:] != expect:
        # a same-element-count layout mismatch (e.g. NCHW) would
        # reshape into scrambled patches and silently fail to learn
        raise ValueError(f"expected NHWC images [b, {expect[0]}, "
                         f"{expect[1]}, {expect[2]}], got "
                         f"{images.shape}")
    b = images.shape[0]
    p, side = cfg.patch_size, cfg.image_size // cfg.patch_size
    x = images.reshape(b, side, p, side, p, cfg.channels)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, side * side, p * p * cfg.channels)


def vit_forward(params: Params, images: jnp.ndarray,
                cfg: ViTConfig) -> jnp.ndarray:
    """[b, H, W, C] float images → [b, num_classes] logits."""
    bc = cfg.block_cfg()
    dt = cfg.dtype
    x = patchify(images.astype(dt), cfg)
    x = x @ params["patch"]["w"].astype(dt) + \
        params["patch"]["b"].astype(dt)
    cls = jnp.broadcast_to(params["cls"].astype(dt),
                           (x.shape[0], 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1) + params["pos"].astype(dt)

    layer = functools.partial(_layer, bc)
    policy = remat_policy(cfg.remat)
    if policy is not None:
        layer = jax.checkpoint(layer, policy=policy)

    def body(h, lp):
        h, _aux = layer(h, lp, None, None)
        return h, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = _norm(bc, x, params["final_norm"], params.get("final_norm_b"))
    cls_out = x[:, 0].astype(jnp.float32)
    return cls_out @ params["head"]["w"].astype(jnp.float32) + \
        params["head"]["b"]


def vit_loss(params: Params, batch: Dict[str, jnp.ndarray],
             cfg: ViTConfig) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    logits = vit_forward(params, batch["image"], cfg)
    labels = batch["label"]
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.take_along_axis(logp, labels[:, None],
                                axis=-1)[:, 0].mean()
    acc = (jnp.argmax(logits, axis=-1) == labels).mean()
    return loss, {"loss": loss, "accuracy": acc}


def make_vit_train_step(cfg: ViTConfig, optimizer):
    """(params, opt_state, batch) → (params, opt_state, metrics); jit
    (or pjit over a mesh with `pytree_shardings`) exactly like the LM
    train step."""
    import optax

    def step(params, opt_state, batch):
        (_, metrics), grads = jax.value_and_grad(
            vit_loss, has_aux=True)(params, batch, cfg)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, metrics

    return step
