"""Model zoo: TPU-first transformer family.

The reference keeps models inside user frameworks (torch modules in Train
examples, small MLP/CNN catalogs in RLlib — `rllib/models/catalog.py`); here
decoder-only transformers are framework citizens: pure-JAX pytrees with
logical sharding axes on every parameter, scan-over-layers bodies, and
Pallas attention (`ray_tpu.ops`).
"""

from .generate import (  # noqa: F401
    cache_gather_slot,
    cache_insert_slot,
    decode_step,
    decode_step_slots,
    draft_propose_slots,
    generate,
    init_kv_cache,
    init_slot_cache,
    prefill,
    prefill_chunk,
    prefill_chunk_jit,
    prefill_chunked,
    resume_prefill,
    verify_step_slots,
)
from .transformer import (  # noqa: F401
    TransformerConfig,
    init_params,
    forward,
    forward_with_aux,
    lm_loss,
    make_train_step,
    count_params,
    flops_per_token,
    decode_flops_per_token,
    engine_flops_table,
)
from .vit import (  # noqa: F401
    ViTConfig,
    init_vit_params,
    make_vit_train_step,
    vit_forward,
    vit_loss,
)
