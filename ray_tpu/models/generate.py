"""Autoregressive generation with a KV cache, fully jitted.

The serving-side decode path behind the BASELINE north star #5 (p50 TTFT
for TP-sharded replicas): prefill runs the prompt once and materializes
per-layer K/V into a fixed-capacity cache; each decode step then attends
one query position against the cache — O(seq) memory traffic instead of
O(seq²) recompute — and the whole prefill + N-step decode loop compiles
into two XLA programs (`prefill`, `lax.scan` of `decode_step`).  The
cache is a pytree of layer-stacked arrays, so pjit shards it with the
same logical rules as the parameters (heads → tp, batch → dp).

Reference: Ray has no model runtime of its own (serving delegates to the
wrapped framework); this module is the TPU-native equivalent of what its
users bring via vLLM/TGI — sized to the in-tree transformer family.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.rotary import apply_rotary, rotary_angles
from .transformer import TransformerConfig, _ffn, _layer, _norm, _unembed

Params = Any
KVCache = Dict[str, jnp.ndarray]   # {"k","v": [L, B, max_len, hk, hd], "pos"}


def init_kv_cache(cfg: TransformerConfig, batch: int,
                  max_len: int) -> KVCache:
    shape = (cfg.n_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "pos": jnp.zeros((), jnp.int32)}


def _check_decodable(cfg: TransformerConfig) -> None:
    if cfg.pp_stages > 1:
        raise NotImplementedError(
            "KV-cache decode over a pipeline mesh is not supported; "
            "serve pp-sharded models stage-per-gang instead")


def _project_kv(cfg, y, lp, cos, sin):
    dt = cfg.dtype
    k = jnp.einsum("bsd,dhk->bshk", y, lp["wk"].astype(dt))
    v = jnp.einsum("bsd,dhk->bshk", y, lp["wv"].astype(dt))
    if cfg.pos_emb == "rope":
        k = apply_rotary(k, cos, sin)
    return k, v


def prefill(params: Params, tokens: jnp.ndarray, cfg: TransformerConfig,
            cache: KVCache) -> Tuple[jnp.ndarray, KVCache]:
    """Run the prompt; → (logits of the LAST position [B, vocab], cache
    holding the prompt's K/V with pos = prompt length)."""
    _check_decodable(cfg)
    b, s = tokens.shape
    dt = cfg.dtype
    x = params["embed"]["tok"][tokens].astype(dt)
    if cfg.pos_emb == "learned":
        x = x + params["embed"]["pos"][:s].astype(dt)
    cos, sin = (rotary_angles(s, cfg.head_dim, cfg.rope_base)
                if cfg.pos_emb == "rope" else (None, None))

    def body(carry, lp):
        h = carry
        # K/V for the cache come from the same pre-norm projection the
        # layer itself computes; run the layer for h, re-project for kv
        y = _norm(cfg, h, lp["attn_norm"], lp.get("attn_norm_b"))
        k, v = _project_kv(cfg, y, lp, cos, sin)
        h, _ = _layer(cfg, h, lp, cos, sin)
        return h, (k, v)

    x, (ks, vs) = jax.lax.scan(body, x, params["layers"])
    x = _norm(cfg, x, params["final_norm"], params.get("final_norm_b"))
    logits = jnp.einsum("bd,dv->bv", x[:, -1], _unembed(params, cfg))

    if s > cache["k"].shape[2]:
        raise ValueError(f"prompt length {s} exceeds cache capacity "
                         f"{cache['k'].shape[2]}")
    cache = {
        "k": jax.lax.dynamic_update_slice(
            cache["k"], ks.astype(cfg.dtype), (0, 0, 0, 0, 0)),
        "v": jax.lax.dynamic_update_slice(
            cache["v"], vs.astype(cfg.dtype), (0, 0, 0, 0, 0)),
        "pos": jnp.asarray(s, jnp.int32),
    }
    return logits.astype(jnp.float32), cache


def prefill_chunk(params: Params, tokens: jnp.ndarray, cache: KVCache,
                  cfg: TransformerConfig) -> Tuple[jnp.ndarray, KVCache]:
    """Extend the cache with a CHUNK of prompt tokens [B, C] starting at
    ``cache['pos']`` → (logits of the chunk's last position, cache').

    The compile-helper-friendly prefill: one program per (B, C) shape,
    reused across a prompt of any length.  A whole-prompt flash prefill
    compiles a program proportional to the full sequence — the
    llama-1b GQA variant of that compile is a known remote-compile-
    helper killer (SURVEY §9); chunking caps the compiled program at C
    positions.  Chunk attention runs dense against the cache's max_len
    (O(C·max_len) per chunk) — more FLOPs than causal flash, traded for
    a bounded, cacheable compile."""
    _check_decodable(cfg)
    b, c = tokens.shape
    dt = cfg.dtype
    pos = cache["pos"]
    max_len = cache["k"].shape[2]
    x = params["embed"]["tok"][tokens].astype(dt)              # [B,C,D]
    if cfg.pos_emb == "learned":
        x = x + jax.lax.dynamic_slice_in_dim(
            params["embed"]["pos"], pos, c, axis=0).astype(dt)
    if cfg.pos_emb == "rope":
        full_cos, full_sin = rotary_angles(max_len, cfg.head_dim,
                                           cfg.rope_base)
        cos = jax.lax.dynamic_slice_in_dim(full_cos, pos, c, axis=0)
        sin = jax.lax.dynamic_slice_in_dim(full_sin, pos, c, axis=0)
    else:
        cos = sin = None

    h, hk, hd = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    # mask[i, t]: cached position t visible to chunk token i (causal
    # within the chunk, everything before it fully visible)
    mask = jnp.arange(max_len)[None, :] <= (pos + jnp.arange(c))[:, None]

    def body(carry, inputs):
        xc = carry
        lp, ck, cv = inputs                                    # per-layer
        y = _norm(cfg, xc, lp["attn_norm"], lp.get("attn_norm_b"))
        q = jnp.einsum("bsd,dhk->bshk", y, lp["wq"].astype(dt))
        if cfg.pos_emb == "rope":
            q = apply_rotary(q, cos, sin)
        k_new, v_new = _project_kv(cfg, y, lp, cos, sin)
        ck = jax.lax.dynamic_update_slice(ck, k_new.astype(cfg.dtype),
                                          (0, pos, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, v_new.astype(cfg.dtype),
                                          (0, pos, 0, 0))
        qh = q.reshape(b, c, hk, h // hk, hd)
        scores = jnp.einsum("bskgd,btkd->bskgt", qh,
                            ck.astype(dt)) / jnp.sqrt(float(hd))
        scores = jnp.where(mask[None, :, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        attn = jnp.einsum("bskgt,btkd->bskgd", probs.astype(dt),
                          cv.astype(dt))
        attn = attn.reshape(b, c, h, hd)
        xc = xc + jnp.einsum("bshk,hkd->bsd", attn,
                             lp["wo"].astype(dt))
        y2 = _norm(cfg, xc, lp["mlp_norm"], lp.get("mlp_norm_b"))
        z, _ = _ffn(cfg, y2, lp)
        xc = xc + z
        return xc, (ck, cv)

    x, (ks, vs) = jax.lax.scan(body, x,
                               (params["layers"], cache["k"], cache["v"]))
    x = _norm(cfg, x, params["final_norm"], params.get("final_norm_b"))
    logits = jnp.einsum("bd,dv->bv", x[:, -1], _unembed(params, cfg))
    return logits.astype(jnp.float32), {"k": ks, "v": vs, "pos": pos + c}


# Module-level jit: every prefill_chunked caller shares one trace/compile
# cache (the point of chunking is a bounded, REUSED program)
_prefill_chunk_jit = jax.jit(prefill_chunk, static_argnames=("cfg",))

#: The ONE shared chunk program behind every prefill path: legacy
#: `prefill_chunked`, failover `resume_prefill`, AND the serve engine's
#: chunked admission (serve/decode_session.py) all dispatch through this
#: handle, so a replica compiles at most two prefill shapes per model
#: config ([B, chunk] blocks + [B, 1] tail steps) no matter how many
#: prompts, resumes, or admissions it serves.
prefill_chunk_jit = _prefill_chunk_jit


def prefill_chunked(params: Params, tokens: jnp.ndarray,
                    cfg: TransformerConfig, cache: KVCache,
                    *, chunk: int = 512,
                    _jitted=None) -> Tuple[jnp.ndarray, KVCache]:
    """Whole-prompt prefill as ceil(s/chunk) reusable chunk programs
    (at most two compiled shapes: ``chunk`` and the tail remainder).
    Drop-in for :func:`prefill` where compile size must stay bounded."""
    b, s = tokens.shape
    if s > cache["k"].shape[2]:
        raise ValueError(f"prompt length {s} exceeds cache capacity "
                         f"{cache['k'].shape[2]}")
    fn = _jitted or _prefill_chunk_jit
    logits = None
    for off in range(0, s, chunk):
        logits, cache = fn(params, tokens[:, off:off + chunk], cache,
                           cfg=cfg)
    return logits, cache


def resume_prefill(params: Params, tokens: jnp.ndarray,
                   cfg: TransformerConfig, cache: KVCache,
                   *, chunk: int = 32,
                   _jitted=None) -> Tuple[jnp.ndarray, KVCache]:
    """Teacher-forced prefix prefill for decode-session failover.

    A resumed session replays ``prompt + tokens-generated-so-far`` into a
    fresh cache, and that prefix has an *arbitrary* length — one compile
    per resume length (the whole-prompt :func:`prefill` behavior) would
    turn every failover into a compile storm.  This walks the prefix
    through exactly TWO reusable chunk programs: ``[B, chunk]`` blocks,
    then ``[B, 1]`` steps for the remainder — so resuming at any point of
    any stream reuses the same compiled code.

    Greedy replay is deterministic: the logits of the last position are
    (numerically) the same the uninterrupted session would have produced,
    so the argmax — the next token — matches exactly."""
    b, s = tokens.shape
    if s > cache["k"].shape[2]:
        raise ValueError(f"resume prefix length {s} exceeds cache "
                         f"capacity {cache['k'].shape[2]}")
    fn = _jitted or _prefill_chunk_jit
    logits = None
    off = 0
    while off + chunk <= s:
        logits, cache = fn(params, tokens[:, off:off + chunk], cache,
                           cfg=cfg)
        off += chunk
    while off < s:
        logits, cache = fn(params, tokens[:, off:off + 1], cache, cfg=cfg)
        off += 1
    return logits, cache


def decode_step(params: Params, token: jnp.ndarray, cache: KVCache,
                cfg: TransformerConfig) -> Tuple[jnp.ndarray, KVCache]:
    """One token [B] int32 → (next-token logits [B, vocab], cache')."""
    _check_decodable(cfg)
    b = token.shape[0]
    dt = cfg.dtype
    pos = cache["pos"]
    max_len = cache["k"].shape[2]
    x = params["embed"]["tok"][token][:, None].astype(dt)     # [B,1,D]
    if cfg.pos_emb == "learned":
        x = x + jax.lax.dynamic_slice_in_dim(
            params["embed"]["pos"], pos, 1, axis=0).astype(dt)
    if cfg.pos_emb == "rope":
        full_cos, full_sin = rotary_angles(max_len, cfg.head_dim,
                                           cfg.rope_base)
        cos = jax.lax.dynamic_slice_in_dim(full_cos, pos, 1, axis=0)
        sin = jax.lax.dynamic_slice_in_dim(full_sin, pos, 1, axis=0)
    else:
        cos = sin = None

    h, hk, hd = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    mask = (jnp.arange(max_len) <= pos)                        # [max_len]

    def body(carry, inputs):
        xc = carry
        lp, ck, cv = inputs                                    # per-layer
        y = _norm(cfg, xc, lp["attn_norm"], lp.get("attn_norm_b"))
        q = jnp.einsum("bsd,dhk->bshk", y, lp["wq"].astype(dt))
        if cfg.pos_emb == "rope":
            q = apply_rotary(q, cos, sin)
        k_new, v_new = _project_kv(cfg, y, lp, cos, sin)
        ck = jax.lax.dynamic_update_slice(ck, k_new.astype(cfg.dtype),
                                          (0, pos, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, v_new.astype(cfg.dtype),
                                          (0, pos, 0, 0))
        # GQA: group query heads over kv heads
        qh = q[:, 0].reshape(b, hk, h // hk, hd)
        scores = jnp.einsum("bkgd,btkd->bkgt", qh,
                            ck.astype(dt)) / jnp.sqrt(float(hd))
        scores = jnp.where(mask[None, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        attn = jnp.einsum("bkgt,btkd->bkgd", probs.astype(dt),
                          cv.astype(dt))
        attn = attn.reshape(b, 1, h, hd)
        xc = xc + jnp.einsum("bshk,hkd->bsd", attn,
                             lp["wo"].astype(dt))
        y2 = _norm(cfg, xc, lp["mlp_norm"], lp.get("mlp_norm_b"))
        z, _ = _ffn(cfg, y2, lp)
        xc = xc + z
        return xc, (ck, cv)

    x, (ks, vs) = jax.lax.scan(body, x,
                               (params["layers"], cache["k"], cache["v"]))
    x = _norm(cfg, x, params["final_norm"], params.get("final_norm_b"))
    logits = jnp.einsum("bd,dv->bv", x[:, 0], _unembed(params, cfg))
    return logits.astype(jnp.float32), {"k": ks, "v": vs, "pos": pos + 1}


def init_slot_cache(cfg: TransformerConfig, slots: int,
                    max_len: int) -> KVCache:
    """KV cache for a continuous-batching decode engine: ``slots``
    independent sessions share one batched program, so ``pos`` is a
    per-slot vector instead of the single scalar of
    :func:`init_kv_cache`."""
    shape = (cfg.n_layers, slots, max_len, cfg.kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "pos": jnp.zeros((slots,), jnp.int32)}


def cache_insert_slot(slot_cache: KVCache, cache: KVCache,
                      slot: jnp.ndarray) -> KVCache:
    """Write a batch-1 session cache (from :func:`prefill`) into slot
    ``slot`` of a slot-batched cache.  ``slot`` is a TRACED index —
    one jitted program serves every slot, so session admission never
    recompiles."""
    return {
        "k": jax.lax.dynamic_update_slice(
            slot_cache["k"], cache["k"].astype(slot_cache["k"].dtype),
            (0, slot, 0, 0, 0)),
        "v": jax.lax.dynamic_update_slice(
            slot_cache["v"], cache["v"].astype(slot_cache["v"].dtype),
            (0, slot, 0, 0, 0)),
        "pos": jax.lax.dynamic_update_slice(
            slot_cache["pos"],
            jnp.reshape(cache["pos"], (1,)).astype(jnp.int32), (slot,)),
    }


def cache_gather_slot(slot_cache: KVCache, slot: jnp.ndarray,
                      upto: jnp.ndarray) -> KVCache:
    """Extract slot ``slot`` of a slot-batched cache as a batch-1 cache
    TRUNCATED to its first ``upto`` positions — the prefix-reuse
    admission primitive (inverse of :func:`cache_insert_slot`).

    A new session whose prompt shares ``upto`` tokens with a live
    slot's prompt seeds its prefill cache from this copy and chunk-
    prefills only the unshared suffix.  The K/V rows at positions >=
    ``upto`` still hold the donor's LATER tokens, but they sit past the
    returned ``pos`` and every prefill/decode program masks reads to
    positions <= pos — the same stale-rows-are-invisible invariant
    paused slots and rejected speculative writes rely on — and the
    suffix prefill overwrites them before ``pos`` ever reaches them.
    ``slot`` and ``upto`` are TRACED, so one compiled program serves
    every (donor slot, prefix length) pair."""
    nl, _, max_len, hk, hd = slot_cache["k"].shape
    k = jax.lax.dynamic_slice(slot_cache["k"], (0, slot, 0, 0, 0),
                              (nl, 1, max_len, hk, hd))
    v = jax.lax.dynamic_slice(slot_cache["v"], (0, slot, 0, 0, 0),
                              (nl, 1, max_len, hk, hd))
    return {"k": k, "v": v, "pos": jnp.asarray(upto, jnp.int32)}


def _rotate_slots(x: jnp.ndarray, cos: jnp.ndarray,
                  sin: jnp.ndarray) -> jnp.ndarray:
    """apply_rotary for PER-SLOT positions: cos/sin are [S, 1, 1, hd//2]
    (one angle row per slot) instead of the shared [seq, hd//2] table.
    Same fp32 rotate-half math, so slot decode matches the batch-1
    path bit-for-bit."""
    x32 = x.astype(jnp.float32)
    half = x.shape[-1] // 2
    x1, x2 = x32[..., :half], x32[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                          axis=-1)
    return out.astype(x.dtype)


def decode_step_slots(params: Params, token: jnp.ndarray, cache: KVCache,
                      active: jnp.ndarray, cfg: TransformerConfig
                      ) -> Tuple[jnp.ndarray, KVCache]:
    """One continuous-batching decode step over ALL slots at once.

    ``token`` [S] int32 (each slot's last token; free/paused slots may
    carry any value), ``cache`` a slot cache with per-slot ``pos`` [S],
    ``active`` [S] bool.  → (logits [S, vocab], cache') where ``pos``
    advances only on active slots.  Inactive slots still compute (the
    batch shape is FIXED — that is what keeps this a single compiled
    program) but their K/V write lands at their un-advanced ``pos`` and
    is overwritten by the next active step before any read, and their
    logits are discarded by the engine.
    """
    _check_decodable(cfg)
    s = token.shape[0]
    dt = cfg.dtype
    pos = cache["pos"]                                         # [S]
    max_len = cache["k"].shape[2]
    x = params["embed"]["tok"][token][:, None].astype(dt)      # [S,1,D]
    if cfg.pos_emb == "learned":
        x = x + params["embed"]["pos"][pos][:, None].astype(dt)
    if cfg.pos_emb == "rope":
        full_cos, full_sin = rotary_angles(max_len, cfg.head_dim,
                                           cfg.rope_base)
        cos = full_cos[pos][:, None, None, :]                  # [S,1,1,·]
        sin = full_sin[pos][:, None, None, :]
    else:
        cos = sin = None

    h, hk, hd = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    slot_ix = jnp.arange(s)
    mask = jnp.arange(max_len)[None, :] <= pos[:, None]        # [S, T]

    def body(carry, inputs):
        xc = carry
        lp, ck, cv = inputs                                    # per-layer
        y = _norm(cfg, xc, lp["attn_norm"], lp.get("attn_norm_b"))
        q = jnp.einsum("bsd,dhk->bshk", y, lp["wq"].astype(dt))
        k_new = jnp.einsum("bsd,dhk->bshk", y, lp["wk"].astype(dt))
        v_new = jnp.einsum("bsd,dhk->bshk", y, lp["wv"].astype(dt))
        if cfg.pos_emb == "rope":
            q = _rotate_slots(q, cos, sin)
            k_new = _rotate_slots(k_new, cos, sin)
        # per-slot write positions: scatter instead of the batch-1
        # path's dynamic_update_slice (slots decode at DIFFERENT pos)
        ck = ck.at[slot_ix, pos].set(k_new[:, 0].astype(cfg.dtype))
        cv = cv.at[slot_ix, pos].set(v_new[:, 0].astype(cfg.dtype))
        qh = q[:, 0].reshape(s, hk, h // hk, hd)
        scores = jnp.einsum("bkgd,btkd->bkgt", qh,
                            ck.astype(dt)) / jnp.sqrt(float(hd))
        scores = jnp.where(mask[:, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        attn = jnp.einsum("bkgt,btkd->bkgd", probs.astype(dt),
                          cv.astype(dt))
        attn = attn.reshape(s, 1, h, hd)
        xc = xc + jnp.einsum("bshk,hkd->bsd", attn,
                             lp["wo"].astype(dt))
        y2 = _norm(cfg, xc, lp["mlp_norm"], lp.get("mlp_norm_b"))
        z, _ = _ffn(cfg, y2, lp)
        xc = xc + z
        return xc, (ck, cv)

    x, (ks, vs) = jax.lax.scan(body, x,
                               (params["layers"], cache["k"], cache["v"]))
    x = _norm(cfg, x, params["final_norm"], params.get("final_norm_b"))
    logits = jnp.einsum("bd,dv->bv", x[:, 0], _unembed(params, cfg))
    return logits.astype(jnp.float32), {
        "k": ks, "v": vs, "pos": pos + active.astype(jnp.int32)}


def draft_propose_slots(params: Params, token: jnp.ndarray,
                        cache: KVCache, active: jnp.ndarray,
                        cfg: TransformerConfig, k: int
                        ) -> Tuple[jnp.ndarray, KVCache]:
    """Draft ``k`` greedy tokens per slot in ONE compiled program.

    The proposer side of speculative decoding: a ``lax.scan`` over
    :func:`decode_step_slots` feeds each argmax back in, so one dispatch
    produces ``k`` proposals per slot regardless of ``k`` — on the
    dispatch-bound serving path that is the entire point (k eager draft
    steps would cost k dispatches and erase the win).

    ``token`` [S] int32 (each slot's pending token), ``cache`` the
    DRAFT model's slot cache whose ``pos`` the engine re-syncs from the
    target cache every iteration (rejected speculative writes are then
    overwritten before any masked read — the same invariant paused
    slots rely on).  → (proposals [S, k], cache') with ``pos`` advanced
    by ``k`` on active slots."""

    def step(carry, _):
        tok, c = carry
        logits, c = decode_step_slots(params, tok, c, active, cfg)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(active, nxt, tok)
        return (nxt, c), nxt

    (_, cache), toks = jax.lax.scan(step, (token, cache), None, length=k)
    return jnp.swapaxes(toks, 0, 1), cache                     # [S, k]


def verify_step_slots(params: Params, tokens: jnp.ndarray,
                      proposals: jnp.ndarray, cache: KVCache,
                      active: jnp.ndarray, cfg: TransformerConfig
                      ) -> Tuple[jnp.ndarray, jnp.ndarray, KVCache]:
    """Speculative-decoding verification: one batched forward over
    ``C`` tokens per slot checks a draft's ``C - 1`` proposals and
    yields 1..C accepted tokens per slot.

    ``tokens`` [S, C] int32 — per slot ``[last_tok, d_1, .., d_{C-1}]``
    (the slot's pending token followed by the draft's proposals);
    ``proposals`` [S, C-1] are the ``d_i`` alone; ``cache`` a slot
    cache with per-slot ``pos`` [S]; ``active`` [S] bool.

    → ``(greedy [S, C], accepted [S], cache')`` where ``greedy[s, i]``
    is the target's argmax after consuming ``tokens[s, :i+1]`` and
    ``accepted[s]`` = 1 + the longest proposal prefix matching that
    greedy chain (clamped to remaining cache capacity) — exactly the
    tokens slot ``s`` emits this iteration, ``greedy[s, :accepted[s]]``.
    ``pos`` advances by ``accepted`` on active slots only.

    Greedy speculative decoding is EXACT: every emitted token is the
    target's own greedy choice given the accepted prefix — the draft
    only decides how many of them one dispatch yields — so the stream
    is byte-identical to plain decode.  K/V of every fed token is
    written at its position; rejected-suffix writes land past the
    advanced ``pos`` and are rewritten (with the true token) before any
    masked read, the same invariant plain decode relies on for paused
    slots.  Writes past ``max_len`` are dropped by XLA scatter
    semantics and ``accepted`` is clamped so emission never outruns the
    cache."""
    _check_decodable(cfg)
    s, c = tokens.shape
    dt = cfg.dtype
    pos = cache["pos"]                                         # [S]
    max_len = cache["k"].shape[2]
    posm = pos[:, None] + jnp.arange(c)[None, :]               # [S, C]
    x = params["embed"]["tok"][tokens].astype(dt)              # [S,C,D]
    if cfg.pos_emb == "learned":
        x = x + params["embed"]["pos"][posm].astype(dt)
    if cfg.pos_emb == "rope":
        full_cos, full_sin = rotary_angles(max_len, cfg.head_dim,
                                           cfg.rope_base)
        cos = full_cos[posm][:, :, None, :]                    # [S,C,1,·]
        sin = full_sin[posm][:, :, None, :]
    else:
        cos = sin = None

    h, hk, hd = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    slot_ix = jnp.arange(s)[:, None]                           # [S, 1]
    # mask[s, i, t]: cached position t visible to fed token i of slot s
    mask = jnp.arange(max_len)[None, None, :] <= posm[:, :, None]

    def body(carry, inputs):
        xc = carry
        lp, ck, cv = inputs                                    # per-layer
        y = _norm(cfg, xc, lp["attn_norm"], lp.get("attn_norm_b"))
        q = jnp.einsum("bsd,dhk->bshk", y, lp["wq"].astype(dt))
        k_new = jnp.einsum("bsd,dhk->bshk", y, lp["wk"].astype(dt))
        v_new = jnp.einsum("bsd,dhk->bshk", y, lp["wv"].astype(dt))
        if cfg.pos_emb == "rope":
            q = _rotate_slots(q, cos, sin)
            k_new = _rotate_slots(k_new, cos, sin)
        ck = ck.at[slot_ix, posm].set(k_new.astype(cfg.dtype))
        cv = cv.at[slot_ix, posm].set(v_new.astype(cfg.dtype))
        qh = q.reshape(s, c, hk, h // hk, hd)
        scores = jnp.einsum("bskgd,btkd->bskgt", qh,
                            ck.astype(dt)) / jnp.sqrt(float(hd))
        scores = jnp.where(mask[:, :, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        attn = jnp.einsum("bskgt,btkd->bskgd", probs.astype(dt),
                          cv.astype(dt))
        attn = attn.reshape(s, c, h, hd)
        xc = xc + jnp.einsum("bshk,hkd->bsd", attn,
                             lp["wo"].astype(dt))
        y2 = _norm(cfg, xc, lp["mlp_norm"], lp.get("mlp_norm_b"))
        z, _ = _ffn(cfg, y2, lp)
        xc = xc + z
        return xc, (ck, cv)

    x, (ks, vs) = jax.lax.scan(body, x,
                               (params["layers"], cache["k"], cache["v"]))
    x = _norm(cfg, x, params["final_norm"], params.get("final_norm_b"))
    logits = jnp.einsum("bsd,dv->bsv", x, _unembed(params, cfg))
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)     # [S, C]
    ok = (greedy[:, :-1] == proposals).astype(jnp.int32)
    accepted = 1 + jnp.sum(jnp.cumprod(ok, axis=1), axis=1)
    accepted = jnp.minimum(accepted,
                           jnp.maximum(max_len - pos, 1)).astype(jnp.int32)
    adv = jnp.where(active, accepted, 0).astype(jnp.int32)
    return greedy, accepted, {"k": ks, "v": vs, "pos": pos + adv}


def _sample(logits: jnp.ndarray, key: jax.Array, greedy: bool,
            temperature: jnp.ndarray, top_k: Optional[int]) -> jnp.ndarray:
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k:
        vals, _ = jax.lax.top_k(logits, top_k)
        cutoff = vals[:, -1:]
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(key, logits).astype(jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_tokens", "greedy",
                                    "top_k", "total"))
def _generate_impl(params, prompt, temperature, key, *, cfg,
                   max_new_tokens, greedy, top_k, total):
    b = prompt.shape[0]
    cache = init_kv_cache(cfg, b, total)
    logits, cache = prefill(params, prompt, cfg, cache)

    # Token t_i samples from the PREVIOUS logits (prefill's for t_1), so
    # only max_new_tokens - 1 decode passes are needed — decoding after
    # the final sample would be a wasted full forward pass.
    def step(carry, _):
        logits, cache, key = carry
        key, skey = jax.random.split(key)
        tok = _sample(logits, skey, greedy, temperature, top_k)
        logits, cache = decode_step(params, tok, cache, cfg)
        return (logits, cache, key), tok

    (logits, _, key), toks = jax.lax.scan(
        step, (logits, cache, key), None, length=max_new_tokens - 1)
    _, skey = jax.random.split(key)
    last = _sample(logits, skey, greedy, temperature, top_k)
    # scan with length=0 yields a [0, B] array, so this is total for
    # every max_new_tokens >= 1
    toks = jnp.concatenate([toks, last[None]], axis=0)
    return jnp.swapaxes(toks, 0, 1)                            # [B, N]


def generate(params: Params, prompt: jnp.ndarray, *,
             cfg: TransformerConfig, max_new_tokens: int,
             temperature: float = 0.0, top_k: Optional[int] = None,
             max_len: Optional[int] = None,
             key: Optional[jax.Array] = None) -> jnp.ndarray:
    """prompt [B, S] int32 → generated tokens [B, max_new_tokens].

    Greedy when ``temperature == 0`` (default), else temperature /
    top-k sampling.  One compiled program: prefill + scanned decode.
    ``temperature`` is a TRACED input — serving different temperatures
    per request does not recompile (only the greedy/sampled switch,
    top_k, and the shape-bearing knobs are static).
    """
    b, s = prompt.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, "
                         f"got {max_new_tokens}")
    total = max_len or (s + max_new_tokens)
    if total < s + max_new_tokens:
        # a short cache would silently clamp writes onto the last slot
        raise ValueError(
            f"max_len={total} < prompt ({s}) + max_new_tokens "
            f"({max_new_tokens})")
    if cfg.pos_emb == "learned" and total > cfg.max_seq_len:
        # dynamic_slice would silently clamp to the last embedding row
        raise ValueError(
            f"prompt + max_new_tokens ({total}) exceeds the learned "
            f"position table ({cfg.max_seq_len})")
    if key is None:
        key = jax.random.PRNGKey(0)
    # the greedy switch must be a concrete host bool (it selects the
    # compiled program); temperature itself stays traced
    greedy = bool(float(temperature) == 0.0)
    return _generate_impl(
        params, prompt, jnp.asarray(temperature, jnp.float32), key,
        cfg=cfg, max_new_tokens=max_new_tokens,
        greedy=greedy, top_k=top_k, total=total)
