"""Driver-facing chaos control: apply/clear/inspect the cluster fault plan.

``apply`` stores the plan in the controller KV (namespace ``chaos``) and
broadcasts it on the ``chaos`` pubsub channel — nodelets re-arm on the
push and forward it to their live workers, so the whole cluster is armed
within one notify fan-out.  Processes spawned later pick the plan up at
registration (nodelets query it after subscribing; workers receive it via
``chaos_update`` or the env-propagated ``chaos_plan`` config flag).

See ``ray_tpu/util/fault_injection.py`` for the rule schema, and the
``ray-tpu chaos`` CLI for the file-based form.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .api import _ensure_initialized
from .util import fault_injection as fi


def apply(plan: List[Dict[str, Any]]) -> int:
    """Arm ``plan`` cluster-wide (and locally, so driver-side sites like
    ``rpc.send`` fire too).  Returns the number of rules applied."""
    core = _ensure_initialized()
    core.controller.call("chaos_plan", {"plan": list(plan)}, timeout=30.0)
    fi.arm(plan)
    return len(plan)


def clear() -> None:
    """Disarm the chaos layer cluster-wide."""
    core = _ensure_initialized()
    core.controller.call("chaos_plan", {"clear": True}, timeout=30.0)
    fi.disarm()


def status() -> Dict[str, Any]:
    """The cluster plan (from the controller KV) plus this process's
    injection counts."""
    core = _ensure_initialized()
    plan: Optional[list] = core.controller.call("chaos_plan", {},
                                                timeout=30.0)
    return {"plan": plan, "armed_locally": fi.ACTIVE is not None,
            "local_injected": fi.injected_counts()}
