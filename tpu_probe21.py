"""Twenty-first staged on-chip probe — MoE train MFU.

The expert-parallel path has virtual-mesh characterization
(PARALLEL_BENCH: ep=8 all_to_all tax 1.13x) but no on-chip train row.
Single chip exercises the MoE COMPUTE path — router, top-k dispatch,
capacity-bounded expert matmuls, Switch aux loss — without the
cross-device all_to_all.  Grid: gpt2-small-with-E4/top-1 and E8/top-2
vs the dense small control at the same microbatch; MFU accounting uses
flops_per_token's active-expert count (top-k experts per token), so
dense and MoE rows are comparable utilization numbers.
"""

import time

from probe_common import ProbeLedger, enable_compile_cache, measure_mfu

OUT = __file__.replace("tpu_probe21.py", "TPU_PROBE21_r05.jsonl")


def main() -> None:
    enable_compile_cache()
    led = ProbeLedger(OUT)
    if not led.claim_or_abort():
        return
    import jax.numpy as jnp

    nr = dict(remat=False, norm_remat=True)
    bf16 = jnp.bfloat16
    for tag, kw, batch in (
            ("small_dense_b8", nr, 8),
            ("small_moe_e4k1_b8",
             dict(nr, n_experts=4, expert_top_k=1), 8),
            ("small_moe_e8k2_b4",
             dict(nr, n_experts=8, expert_top_k=2), 4),
            ("small_moe_e8k2_b2",
             dict(nr, n_experts=8, expert_top_k=2), 2),
    ):
        import os
        if tag in ("small_dense_b8", "small_moe_e4k1_b8",
                   "small_moe_e8k2_b4") \
                and os.path.exists(OUT) and tag in open(OUT).read():
            continue                    # already landed in a prior run
        led.guarded(f"mfu:{tag}")(measure_mfu)(
            led, tag, kw, batch, blocks=(1024, 1024), mu_dtype=bf16)

    led.emit("done", {"total_s": round(time.perf_counter() - led.t0, 1)})


if __name__ == "__main__":
    main()
