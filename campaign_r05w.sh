#!/bin/bash
# stage W: final live validation bench (medium headline + 3 scaling rows
# incl. llama-1b).
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9
echo "=== stage W bench $(date -u +%H:%M:%S) ===" >> campaign_r05.log
python bench.py > BENCH_live_r05_interim.json 2>> campaign_r05.log
echo "stage W bench rc=$? $(date -u +%H:%M:%S)" >> campaign_r05.log
