"""Single-node bulk-ingest throughput benchmark (VERDICT r4 missing #2).

Reference methodology: the AIR bulk-ingest benchmark reads parquet,
applies a trivial map_batches, and consumes every block — 0.51 GiB/s on
one m5.4xlarge (16 vCPU) (`/root/reference/doc/source/ray-air/
benchmarks.rst:30-46`, release/air_tests data_ingest).  Same shape here:
generate N GiB of parquet, then time read_parquet → map_batches →
full consumption through the object store.  Writes DATA_BENCH.json.

Run: JAX_PLATFORMS=cpu python bench_data.py [--gib 4]
"""

import argparse
import json
import os
import shutil
import subprocess
import tempfile
import time


def generate_parquet(root: str, gib: float, files: int) -> float:
    """Write ~gib GiB of parquet across ``files`` files; returns bytes."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(root, exist_ok=True)
    rows_per_file = int(gib * 1024**3 / files / 80)  # ~80B/row of floats
    rng = np.random.default_rng(0)
    total = 0
    for i in range(files):
        cols = {f"f{j}": rng.random(rows_per_file) for j in range(8)}
        cols["key"] = rng.integers(0, 1 << 30, rows_per_file)
        cols["label"] = rng.integers(0, 2, rows_per_file)
        table = pa.table(cols)
        path = os.path.join(root, f"part-{i:04d}.parquet")
        pq.write_table(table, path, compression="NONE")
        total += os.path.getsize(path)
    return total


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--gib", type=float, default=4.0)
    ap.add_argument("--files", type=int, default=64)
    ap.add_argument("--out", default="DATA_BENCH.json")
    args = ap.parse_args()

    import ray_tpu
    from ray_tpu import data as rdata

    root = os.path.join(tempfile.gettempdir(), "ingest_bench")
    shutil.rmtree(root, ignore_errors=True)
    t0 = time.perf_counter()
    nbytes = generate_parquet(root, args.gib, args.files)
    gen_s = time.perf_counter() - t0
    gib = nbytes / 1024**3
    print(f"generated {gib:.2f} GiB parquet in {gen_s:.1f}s")

    ray_tpu.init(num_cpus=8,
                 object_store_memory=int((args.gib + 2) * 1024**3))

    # --- bulk ingest: read -> trivial map_batches -> consume all blocks ---
    t0 = time.perf_counter()
    ds = rdata.read_parquet(
        [os.path.join(root, f) for f in sorted(os.listdir(root))])

    def add_one(batch):
        batch["f0"] = batch["f0"] + 1.0
        return batch

    ds = ds.map_batches(add_one)
    consumed_rows = 0
    consumed_bytes = 0
    for batch in ds.iter_batches(batch_size=65536):
        col = next(iter(batch.values()))
        consumed_rows += len(col)
        consumed_bytes += sum(
            getattr(v, "nbytes", 0) for v in batch.values())
    ingest_s = time.perf_counter() - t0
    rate = gib / ingest_s
    print(f"[data] ingest {gib:.2f} GiB in {ingest_s:.1f}s -> "
          f"{rate:.2f} GiB/s ({consumed_rows} rows)")

    commit = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                            capture_output=True, text=True,
                            cwd=os.path.dirname(os.path.abspath(__file__))
                            ).stdout.strip()
    result = {
        "bench": "bulk_ingest_single_node",
        "gib": round(gib, 3),
        "seconds": round(ingest_s, 1),
        "gib_per_s": round(rate, 3),
        "rows": consumed_rows,
        "consumed_gib": round(consumed_bytes / 1024**3, 3),
        "reference": {"value_gib_s": 0.51, "hardware": "1x m5.4xlarge "
                      "(16 vCPU)", "source":
                      "doc/source/ray-air/benchmarks.rst:30-46"},
        "hardware": "1 shared CPU core (this image)",
        "commit": commit,
    }
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           args.out), "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    ray_tpu.shutdown()
    shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
