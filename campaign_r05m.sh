#!/bin/bash
# Round-5 campaign, stage M: probe17 (SSE streamed decode on-chip), then
# a live validation of the new gpt2-medium headline recipe.
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9

ok17 () {
    [ -f TPU_PROBE17_r05.jsonl ] \
        && grep '"stage": "serve_stream"' TPU_PROBE17_r05.jsonl \
           | grep -qv '"error"'
}

tries=0
while [ $tries -lt 6 ]; do
    tries=$((tries+1))
    echo "=== probe17 attempt $tries $(date -u +%H:%M:%S) ===" >> probe17_r05.err
    python tpu_probe17.py >> probe17_r05.out 2>> probe17_r05.err
    if ok17; then
        echo "=== probe17 landed $(date -u +%H:%M:%S) ===" >> probe17_r05.err
        break
    fi
    sleep 240
done

echo "=== stage M bench (gpt2-medium headline) $(date -u +%H:%M:%S) ===" >> campaign_r05.log
python bench.py > BENCH_live_r05_interim.json 2>> campaign_r05.log
echo "stage M bench rc=$? $(date -u +%H:%M:%S)" >> campaign_r05.log
