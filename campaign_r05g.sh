#!/bin/bash
# Round-5 campaign, stage G: queued on the serial flock; runs probe15
# (gradient-accumulation MFU grid — the last single-chip lever).
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9

ok15 () {
    [ -f TPU_PROBE15_r05.jsonl ] \
        && grep '"stage": "mfu"' TPU_PROBE15_r05.jsonl \
           | grep -v '"error"' | grep -q 'medium_m'
}

tries=0
while [ $tries -lt 10 ]; do
    tries=$((tries+1))
    echo "=== probe15 attempt $tries $(date -u +%H:%M:%S) ===" >> probe15_r05.err
    python tpu_probe15.py >> probe15_r05.out 2>> probe15_r05.err
    if ok15; then
        echo "=== probe15 landed $(date -u +%H:%M:%S) ===" >> probe15_r05.err
        break
    fi
    if [ -f TPU_PROBE15_r05.jsonl ] && ! ok15; then
        mv TPU_PROBE15_r05.jsonl "TPU_PROBE15_r05.abort.$tries"
    fi
    sleep 240
done
echo "stage G done $(date -u +%H:%M:%S)" >> campaign_r05.log
