"""Second staged on-chip probe — follow-ups from TPU_PROBE_r04.jsonl.

Same discipline as tpu_probe.py (ONE claim, every stage guarded, every
result fsync'd to TPU_PROBE2_r04.jsonl immediately, never killed).
Changes from probe 1's lessons:
  * RL-on-TPU runs FIRST (small compiles; probe 1 never reached it —
    the llama-1b GQA flash compile hung the remote helper for 50 min
    and the stage after it sat behind the wreckage)
  * the generation stage uses attention_impl="reference" and tries
    llama-tiny before llama-1b (prefill at seq 512 doesn't need the
    flash kernel; the unfused path compiles like every other jit)
  * MFU follow-ups on the winning recipe: b16 with 1024x512 blocks,
    1024x1024 blocks, and a seq-2048 variant
"""

import json
import os
import time
import traceback

T0 = time.perf_counter()
OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "TPU_PROBE2_r04.jsonl")


def log(msg: str) -> None:
    print(f"[probe2 {time.perf_counter() - T0:7.1f}s] {msg}", flush=True)


def emit(stage: str, payload: dict) -> None:
    rec = {"stage": stage, "t": round(time.perf_counter() - T0, 1)}
    rec.update(payload)
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())
    log(f"{stage}: {payload}")


def guarded(stage):
    def deco(fn):
        def run(*a, **kw):
            try:
                return fn(*a, **kw)
            except Exception as exc:
                emit(stage, {"error": repr(exc)[:300],
                             "tb": traceback.format_exc(limit=3)[-400:]})
                return None
        return run
    return deco


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_compile_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    from ray_tpu.models import (TransformerConfig, flops_per_token,
                                init_params, make_train_step)

    backend = jax.default_backend()
    dev = jax.devices()[0]
    emit("env", {"backend": backend,
                 "device": getattr(dev, "device_kind", "?")})
    if backend != "tpu":
        emit("abort", {"reason": f"backend={backend}, not tpu"})
        return
    peak = 197e12 if "v5" in dev.device_kind else 275e12

    # ---- stage 1: canary + RL on the chip -------------------------------
    @guarded("rl_tpu")
    def rl_tpu():
        from ray_tpu.rl import CartPole, PPOConfig
        algo = PPOConfig(env=CartPole, num_envs=128, rollout_length=128,
                         lr=1e-3, seed=0).build()
        algo.train()                      # compile + warmup
        t0 = time.perf_counter()
        steps = 0
        iters = 0
        while time.perf_counter() - t0 < 8.0 or iters < 3:
            res = algo.train()
            steps += res["env_steps_this_iter"]
            iters += 1
        dt = time.perf_counter() - t0
        emit("rl_tpu", {"algo": "PPO", "env": "CartPole",
                        "env_steps_per_s": round(steps / dt, 1),
                        "iters": iters, "backend": jax.default_backend(),
                        "reward": round(res["episode_reward_mean"], 1)})
        return True

    if rl_tpu() is None:
        # even the small PPO compile failed: the backend is unhealthy,
        # don't burn the claim on the rest
        emit("abort", {"reason": "rl stage failed; backend unhealthy"})
        return

    @guarded("rl_dqn_tpu")
    def rl_dqn_tpu():
        from ray_tpu.rl import CartPole, DQNConfig
        algo = DQNConfig(env=CartPole, num_envs=128, rollout_steps=32,
                         buffer_capacity=100_000, batch_size=256,
                         num_updates=16, learn_start=1024, seed=0).build()
        algo.train()
        t0 = time.perf_counter()
        steps = 0
        iters = 0
        while time.perf_counter() - t0 < 6.0 or iters < 3:
            res = algo.train()
            steps += res["env_steps_this_iter"]
            iters += 1
        dt = time.perf_counter() - t0
        emit("rl_dqn_tpu", {"algo": "DQN(double)",
                            "env_steps_per_s": round(steps / dt, 1),
                            "iters": iters})

    rl_dqn_tpu()

    # ---- stage 2: MFU follow-ups on the winning recipe -------------------
    def measure_mfu(tag, cfg_kw, batch, steps=12, seq=1024,
                    blocks=(1024, 512), mu_dtype=None):
        t_stage = time.perf_counter()
        os.environ["RAY_TPU_FLASH_BLOCK_Q"] = str(blocks[0])
        os.environ["RAY_TPU_FLASH_BLOCK_K"] = str(blocks[1])
        cfg = TransformerConfig.gpt2("small", loss_chunk=128,
                                     max_seq_len=max(1024, seq), **cfg_kw)
        params, _ = init_params(jax.random.PRNGKey(0), cfg)
        # mu_dtype=bf16 halves the Adam first-moment's HBM traffic
        opt = optax.adamw(3e-4, weight_decay=0.1, mu_dtype=mu_dtype)
        opt_state = opt.init(params)
        step = jax.jit(make_train_step(cfg, opt), donate_argnums=(0, 1))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq),
                                    0, cfg.vocab_size)
        data = {"tokens": tokens}
        for _ in range(2):
            params, opt_state, m = step(params, opt_state, data)
        float(m["loss"])
        compile_s = time.perf_counter() - t_stage
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, m = step(params, opt_state, data)
        float(m["loss"])
        dt = time.perf_counter() - t0
        mfu = steps * batch * seq / dt * flops_per_token(cfg, seq) / peak
        if not (0.0 < mfu < 0.95):
            t0 = time.perf_counter()
            for _ in range(steps):
                params, opt_state, m = step(params, opt_state, data)
                float(m["loss"])
            dt = time.perf_counter() - t0
            mfu = steps * batch * seq / dt \
                * flops_per_token(cfg, seq) / peak
        emit("mfu", {"tag": tag, "batch": batch, "seq": seq,
                     "blocks": list(blocks), "mfu": round(mfu, 4),
                     "step_ms": round(1000 * dt / steps, 1),
                     "tok_s": round(steps * batch * seq / dt),
                     "compile_s": round(compile_s, 1)})
        del params, opt_state, step, tokens, data
        return mfu

    nr = dict(remat=False, norm_remat=True)
    for tag, kw, batch, seq, blocks, mu in (
            ("b8_confirm", nr, 8, 1024, (1024, 512), None),
            ("b16_bigblocks", nr, 16, 1024, (1024, 512), None),
            ("b8_1024x1024", nr, 8, 1024, (1024, 1024), None),
            ("b16_1024x1024", nr, 16, 1024, (1024, 1024), None),
            ("b8_bf16mu", nr, 8, 1024, (1024, 512), "bfloat16"),
            ("b16_bf16mu", nr, 16, 1024, (1024, 512), "bfloat16"),
            ("b4_seq2048", nr, 4, 2048, (1024, 512), None),
            ("b8_seq2048_dots", dict(remat="dots", norm_remat=True), 8,
             2048, (1024, 512), None),
    ):
        guarded(f"mfu:{tag}")(measure_mfu)(
            tag, kw, batch, seq=seq, blocks=blocks,
            mu_dtype=jnp.bfloat16 if mu else None)
    os.environ.pop("RAY_TPU_FLASH_BLOCK_Q", None)
    os.environ.pop("RAY_TPU_FLASH_BLOCK_K", None)

    # ---- stage 3: generation TTFT/decode (reference attention) ----------
    def gen_stage(tag, cfg, prompt_len, decode_n):
        from ray_tpu.models.generate import (decode_step, init_kv_cache,
                                             prefill)
        t_init = time.perf_counter()
        params, _ = init_params(jax.random.PRNGKey(0), cfg)
        params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if x.dtype == jnp.float32 else x, params)
        jax.block_until_ready(params)
        init_s = time.perf_counter() - t_init
        tokens = jax.random.randint(jax.random.PRNGKey(1),
                                    (1, prompt_len), 0, cfg.vocab_size)
        cache_len = prompt_len + decode_n + 32
        pre = jax.jit(lambda p, t: prefill(p, t, cfg,
                                           init_kv_cache(cfg, 1,
                                                         cache_len)))
        logits, cache = pre(params, tokens)
        jax.block_until_ready(logits)          # compile
        t0 = time.perf_counter()
        logits, cache = pre(params, tokens)
        jax.block_until_ready(logits)
        ttft = time.perf_counter() - t0
        dec = jax.jit(lambda p, tok, c: decode_step(p, tok, c, cfg))
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        lg, cache = dec(params, tok, cache)
        jax.block_until_ready(lg)
        t0 = time.perf_counter()
        for _ in range(decode_n):
            tok = jnp.argmax(lg, -1).astype(jnp.int32)
            lg, cache = dec(params, tok, cache)
        jax.block_until_ready(lg)
        dt = time.perf_counter() - t0
        emit("gen", {"tag": tag, "prompt_len": prompt_len,
                     "prefill_ms": round(ttft * 1e3, 1),
                     "decode_ms_per_tok": round(dt / decode_n * 1e3, 2),
                     "decode_tok_s": round(decode_n / dt, 1),
                     "param_init_s": round(init_s, 1)})

    guarded("gen:gpt2s")(gen_stage)(
        "gpt2-small bf16",
        TransformerConfig.gpt2("small", remat=False,
                               attention_impl="reference"), 256, 64)
    guarded("gen:llama_tiny")(gen_stage)(
        "llama-tiny bf16",
        TransformerConfig.llama("tiny", max_seq_len=1024, remat=False,
                                attention_impl="reference"), 512, 64)
    guarded("gen:llama_1b")(gen_stage)(
        "llama-1b bf16",
        TransformerConfig.llama("1b", max_seq_len=1024, remat=False,
                                attention_impl="reference"), 512, 64)

    emit("done", {"total_s": round(time.perf_counter() - T0, 1)})


if __name__ == "__main__":
    main()
