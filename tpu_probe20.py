"""Twentieth staged on-chip probe — the image-model family (ViT-B/16).

BASELINE config #2 is the image-training class (the reference's
published rows are ResNet: 40.7→746.3 images/s TRAIN across 1→4 GPU
nodes, 35.2→533.9 images/s batch-predict,
/root/reference/doc/source/ray-air/benchmarks.rst:119-174).  The
framework's vision family is ViT (models/vit.py, ViT-B/16 = 86M);
this probe puts train MFU + images/s and forward-only batch-predict
images/s on the board for ONE v5e chip.

MFU accounting: encoder-layer FLOPs via the shared
flops_per_token(block_cfg, seq=197) x 197 tokens/image (patch/head
matmuls add ~1%, uncounted — MFU is slightly understated).
"""

import time

from probe_common import ProbeLedger, enable_compile_cache

OUT = __file__.replace("tpu_probe20.py", "TPU_PROBE20_r05.jsonl")


def main() -> None:
    enable_compile_cache()
    led = ProbeLedger(OUT)
    if not led.claim_or_abort():
        return
    import jax
    import jax.numpy as jnp
    import optax

    from bench import _peak_flops, timed_mfu_loop
    from ray_tpu.models import flops_per_token
    from ray_tpu.models.vit import (ViTConfig, init_vit_params,
                                    make_vit_train_step, vit_forward)

    peak = _peak_flops(jax.devices()[0])
    cfg = ViTConfig.base()                      # ViT-B/16, 224x224
    flops_img = flops_per_token(cfg.block_cfg(), cfg.seq_len) \
        * cfg.seq_len

    def train_stage(tag, batch, steps=10):
        t0 = time.perf_counter()
        params, _ = init_vit_params(jax.random.PRNGKey(0), cfg)
        opt = optax.adamw(3e-4, weight_decay=0.1,
                          mu_dtype=jnp.bfloat16)
        opt_state = opt.init(params)
        step = jax.jit(make_vit_train_step(cfg, opt),
                       donate_argnums=(0, 1))
        data = {
            "image": jax.random.normal(
                jax.random.PRNGKey(1),
                (batch, cfg.image_size, cfg.image_size, cfg.channels),
                jnp.bfloat16),
            "label": jax.random.randint(jax.random.PRNGKey(2),
                                        (batch,), 0, cfg.num_classes),
        }
        for _ in range(2):
            params, opt_state, m = step(params, opt_state, data)
        float(m["loss"])
        compile_s = time.perf_counter() - t0
        mfu, dt, params, opt_state = timed_mfu_loop(
            step, params, opt_state, data, steps, batch, flops_img,
            peak)
        led.emit("mfu", {"tag": tag, "model": "vit-b16", "batch": batch,
                         "mfu": round(mfu, 4),
                         "images_per_s": round(steps * batch / dt, 1),
                         "step_ms": round(1000 * dt / steps, 1),
                         "compile_s": round(compile_s, 1)})

    def predict_stage(tag, batch, steps=16):
        params, _ = init_vit_params(jax.random.PRNGKey(0), cfg)
        fwd = jax.jit(lambda p, x: vit_forward(p, x, cfg))
        x = jax.random.normal(
            jax.random.PRNGKey(1),
            (batch, cfg.image_size, cfg.image_size, cfg.channels),
            jnp.bfloat16)
        out = fwd(params, x)
        float(jnp.max(out))                    # compile + barrier
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fwd(params, x)
        float(jnp.max(out))
        dt = time.perf_counter() - t0
        led.emit("predict", {"tag": tag, "model": "vit-b16",
                             "batch": batch,
                             "images_per_s":
                                 round(steps * batch / dt, 1),
                             "ms_per_batch":
                                 round(1000 * dt / steps, 2)})

    led.guarded("mfu:vit_b64")(train_stage)("vit_b64", 64)
    led.guarded("mfu:vit_b128")(train_stage)("vit_b128", 128)
    led.guarded("mfu:vit_b256")(train_stage)("vit_b256", 256)
    led.guarded("predict:vit_b256")(predict_stage)("vit_pred_b256", 256)

    led.emit("done", {"total_s": round(time.perf_counter() - led.t0, 1)})


if __name__ == "__main__":
    main()
