"""Eighth staged on-chip probe — larger-model MFU.

Bigger d_model means more FLOPs per HBM byte, so gpt2-medium/large
should sit HIGHER on the roofline than small's 0.37 at the same
recipe — a shot at crossing the 0.40 north star outright (the BASELINE
metric stays gpt2-small; this is the scaling evidence).  Memory: at
b8/s1024, medium (350M) fits like small's b16 did; large (774M) only
with selective remat — both staged guarded, OOM just fails the stage.

Uses the shared probe_common harness.  Same discipline: ONE claim,
guarded stages, fsync'd ledger, never kill.
"""

import time

from probe_common import ProbeLedger, enable_compile_cache, measure_mfu

OUT = __file__.replace("tpu_probe8.py", "TPU_PROBE8_r05.jsonl")


def main() -> None:
    enable_compile_cache()
    led = ProbeLedger(OUT)
    if not led.claim_or_abort():
        return
    import jax.numpy as jnp

    nr = dict(remat=False, norm_remat=True)
    bf16 = jnp.bfloat16
    for tag, preset, kw, batch, mu in (
            ("medium_b4", "medium", nr, 4, bf16),
            ("medium_b8", "medium", nr, 8, bf16),
            ("medium_b16", "medium", nr, 16, bf16),
            ("large_b2", "large", nr, 2, bf16),
            ("large_b4_dots", "large",
             dict(remat="dots", norm_remat=True), 4, bf16),
    ):
        led.guarded(f"mfu:{tag}")(measure_mfu)(
            led, tag, kw, batch, blocks=(1024, 1024), mu_dtype=mu,
            preset=preset)

    led.emit("done", {"total_s": round(time.perf_counter() - led.t0, 1)})


if __name__ == "__main__":
    main()
