"""Thirteenth staged on-chip probe — the remaining MFU cells.

probe8 landed gpt2-medium b4 at 0.3839 (above small's 0.3702 official,
confirming bigger d_model sits higher on the roofline) but b8/b16 and
both large cells OOM'd the 16 GiB chip.  This grid fills the untried
memory/batch cells between those points:

  * medium b5/b6 — the largest batch that fits decides medium's
    single-chip ceiling (b4 fits easily, b8 barely OOMs)
  * medium b4 + loss_chunk 256 — chunk sweep at the new operating point
  * medium b2 @ seq2048 — same tokens as b4@1024, attention fraction up
  * large b2 with dots remat / large b1 without — the two unexplored
    large cells (probe8 only tried b2-no-remat and b4-dots, both OOM)

Uses the shared probe_common harness.  Same discipline: ONE claim,
guarded stages, fsync'd ledger, never kill.
"""

import time

from probe_common import ProbeLedger, enable_compile_cache, measure_mfu

OUT = __file__.replace("tpu_probe13.py", "TPU_PROBE13_r05.jsonl")


def main() -> None:
    enable_compile_cache()
    led = ProbeLedger(OUT)
    if not led.claim_or_abort():
        return
    import jax.numpy as jnp

    nr = dict(remat=False, norm_remat=True)
    bf16 = jnp.bfloat16
    for tag, preset, kw, batch, seq in (
            ("medium_b6", "medium", nr, 6, 1024),
            ("medium_b5", "medium", nr, 5, 1024),
            ("medium_b4_chunk256", "medium", dict(nr, loss_chunk=256), 4,
             1024),
            ("medium_b2_seq2048", "medium", nr, 2, 2048),
            ("large_b2_dots", "large",
             dict(remat="dots", norm_remat=True), 2, 1024),
            ("large_b1", "large", nr, 1, 1024),
    ):
        led.guarded(f"mfu:{tag}")(measure_mfu)(
            led, tag, kw, batch, seq=seq, blocks=(1024, 1024),
            mu_dtype=bf16, preset=preset)

    led.emit("done", {"total_s": round(time.perf_counter() - led.t0, 1)})


if __name__ == "__main__":
    main()
