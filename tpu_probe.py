"""Staged on-chip ablation probe — the round-4 perf campaign.

One process, ONE chip claim, many stages; every stage's result is
APPENDED to ``TPU_PROBE_r04.jsonl`` the moment it lands (a later stage's
hang can never lose an earlier result).  Never kill this process
externally: a killed claimant wedges the tunnelled grant until timeout
(the round-3 lesson, encoded in bench.py's discipline).

Stages (VERDICT-r3 asks #1 and #3):
  1. canary           — tiny-model compile+step; proves the claim is live
  2. mfu grid         — GPT-2-small train-step MFU over the staged
                        ablations: norm-save dtype (norm_remat), batch
                        16/32, one-hot embed, remat="dots"
  3. flash blocks     — block_q/block_k sweep on the best mfu config
  4. llama TTFT       — llama-1b prefill latency + decode tok/s (north
                        star #5's model side; serving-path overhead is
                        measured separately by bench.py --serve)
  5. rl-on-tpu        — PPO env-steps/s with the learner on the chip

Reference methodology anchor: the reference publishes its benchmark
story the same staged way (/root/reference/release/benchmarks/README.md:5,
/root/reference/doc/source/ray-air/benchmarks.rst:178).
"""

import json
import os
import time
import traceback

T0 = time.perf_counter()
OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "TPU_PROBE_r04.jsonl")


def log(msg: str) -> None:
    print(f"[probe {time.perf_counter() - T0:7.1f}s] {msg}", flush=True)


def emit(stage: str, payload: dict) -> None:
    rec = {"stage": stage, "t": round(time.perf_counter() - T0, 1)}
    rec.update(payload)
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())
    log(f"{stage}: {payload}")


def guarded(stage):
    def deco(fn):
        def run(*a, **kw):
            try:
                return fn(*a, **kw)
            except Exception as exc:
                emit(stage, {"error": repr(exc)[:300],
                             "tb": traceback.format_exc(limit=3)[-400:]})
                return None
        return run
    return deco


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_compile_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    from ray_tpu.models import (TransformerConfig, flops_per_token,
                                init_params, make_train_step)

    backend = jax.default_backend()
    dev = jax.devices()[0]
    emit("env", {"backend": backend,
                 "device": getattr(dev, "device_kind", "?")})
    if backend != "tpu":
        emit("abort", {"reason": f"backend={backend}, not tpu"})
        return
    peak = 197e12 if "v5" in dev.device_kind else 275e12

    # ---- stage 1: canary ------------------------------------------------
    @guarded("canary")
    def canary():
        cfg = TransformerConfig.tiny(d_model=256)
        p, _ = init_params(jax.random.PRNGKey(0), cfg)
        opt = optax.adamw(3e-4)
        step = jax.jit(make_train_step(cfg, opt))
        tok = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0,
                                 cfg.vocab_size)
        p2, _, m = step(p, opt.init(p), {"tokens": tok})
        emit("canary", {"ok": True, "loss": round(float(m["loss"]), 3)})
        return True

    if not canary():
        return

    # ---- stage 2: MFU grid ---------------------------------------------
    def measure_mfu(tag: str, cfg_kw: dict, batch: int, steps: int = 12,
                    seq: int = 1024) -> float:
        """One train-step MFU measurement; emits its own record."""
        t_stage = time.perf_counter()
        cfg = TransformerConfig.gpt2("small", loss_chunk=128, **cfg_kw)
        params, _ = init_params(jax.random.PRNGKey(0), cfg)
        opt = optax.adamw(3e-4, weight_decay=0.1)
        opt_state = opt.init(params)
        step = jax.jit(make_train_step(cfg, opt), donate_argnums=(0, 1))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq),
                                    0, cfg.vocab_size)
        data = {"tokens": tokens}
        for _ in range(2):     # compile + warmup
            params, opt_state, m = step(params, opt_state, data)
        float(m["loss"])
        compile_s = time.perf_counter() - t_stage
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, m = step(params, opt_state, data)
        float(m["loss"])
        dt = time.perf_counter() - t0
        mfu = steps * batch * seq / dt * flops_per_token(cfg, seq) / peak
        if not (0.0 < mfu < 0.95):   # async dispatch outran the chip
            t0 = time.perf_counter()
            for _ in range(steps):
                params, opt_state, m = step(params, opt_state, data)
                float(m["loss"])
            dt = time.perf_counter() - t0
            mfu = steps * batch * seq / dt * flops_per_token(cfg, seq) / peak
        emit("mfu", {"tag": tag, "batch": batch, "mfu": round(mfu, 4),
                     "step_ms": round(1000 * dt / steps, 1),
                     "tok_s": round(steps * batch * seq / dt),
                     "compile_s": round(compile_s, 1), "cfg": cfg_kw})
        # free HBM before the next variant compiles
        del params, opt_state, step, tokens, data
        return mfu

    grid = [
        # (tag, cfg_kw, batch) — round-3 baseline first for comparability
        ("b8_base", dict(remat=False), 8),
        ("b8_normremat", dict(remat=False, norm_remat=True), 8),
        ("b16_normremat", dict(remat=False, norm_remat=True), 16),
        ("b16_nr_onehot", dict(remat=False, norm_remat=True,
                               embed_impl="one_hot"), 16),
        ("b32_dots", dict(remat="dots"), 32),
        ("b32_dots_nr", dict(remat="dots", norm_remat=True), 32),
    ]
    best = (None, 0.0, None)    # (tag, mfu, (cfg_kw, batch))
    for tag, kw, batch in grid:
        r = guarded(f"mfu:{tag}")(measure_mfu)(tag, kw, batch)
        if r is not None and r > best[1]:
            best = (tag, r, (kw, batch))

    emit("mfu_best", {"tag": best[0], "mfu": round(best[1], 4)})

    # ---- stage 3: flash block sweep on the best config ------------------
    if best[2] is not None:
        kw, batch = best[2]
        for bq, bk in ((256, 512), (512, 512), (256, 1024), (512, 1024),
                       (128, 512), (1024, 512)):
            os.environ["RAY_TPU_FLASH_BLOCK_Q"] = str(bq)
            os.environ["RAY_TPU_FLASH_BLOCK_K"] = str(bk)
            guarded(f"blocks:{bq}x{bk}")(measure_mfu)(
                f"blocks_{bq}x{bk}", kw, batch, steps=8)
        os.environ.pop("RAY_TPU_FLASH_BLOCK_Q", None)
        os.environ.pop("RAY_TPU_FLASH_BLOCK_K", None)

    # ---- stage 4: llama-1b prefill TTFT + decode tok/s ------------------
    @guarded("llama_gen")
    def llama_gen():
        from ray_tpu.models.generate import (decode_step, init_kv_cache,
                                             prefill)
        cfg = TransformerConfig.llama("1b", max_seq_len=2048,
                                      remat=False)
        t_init = time.perf_counter()
        params, _ = init_params(jax.random.PRNGKey(0), cfg)
        params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if x.dtype == jnp.float32 else x, params)
        jax.block_until_ready(params)
        init_s = time.perf_counter() - t_init
        prompt_len, decode_n = 512, 64
        tokens = jax.random.randint(jax.random.PRNGKey(1),
                                    (1, prompt_len), 0, cfg.vocab_size)
        pre = jax.jit(lambda p, t: prefill(p, t, cfg,
                                           init_kv_cache(cfg, 1, 2048)))
        logits, cache = pre(params, tokens)
        jax.block_until_ready(logits)          # compile
        t0 = time.perf_counter()
        logits, cache = pre(params, tokens)
        jax.block_until_ready(logits)
        ttft = time.perf_counter() - t0
        dec = jax.jit(lambda p, tok, c: decode_step(p, tok, c, cfg))
        tok = jnp.argmax(logits, -1).astype(jnp.int32)       # [B]
        lg, cache = dec(params, tok, cache)    # compile
        jax.block_until_ready(lg)
        t0 = time.perf_counter()
        for _ in range(decode_n):
            tok = jnp.argmax(lg, -1).astype(jnp.int32)
            lg, cache = dec(params, tok, cache)
        jax.block_until_ready(lg)
        dt = time.perf_counter() - t0
        emit("llama_gen", {
            "model": "llama-1b bf16", "prompt_len": prompt_len,
            "prefill_ms": round(ttft * 1e3, 1),
            "decode_ms_per_tok": round(dt / decode_n * 1e3, 2),
            "decode_tok_s": round(decode_n / dt, 1),
            "param_init_s": round(init_s, 1)})

    llama_gen()

    # ---- stage 5: RL on the chip ----------------------------------------
    @guarded("rl_tpu")
    def rl_tpu():
        from ray_tpu.rl import CartPole, PPOConfig
        algo = PPOConfig(env=CartPole, num_envs=128, rollout_length=128,
                         lr=1e-3, seed=0).build()
        algo.train()                      # compile + warmup
        t0 = time.perf_counter()
        steps = 0
        iters = 0
        while time.perf_counter() - t0 < 8.0 or iters < 3:
            res = algo.train()
            steps += res["env_steps_this_iter"]
            iters += 1
        dt = time.perf_counter() - t0
        emit("rl_tpu", {"algo": "PPO", "env": "CartPole",
                        "env_steps_per_s": round(steps / dt, 1),
                        "iters": iters, "backend": jax.default_backend(),
                        "reward": round(res["episode_reward_mean"], 1)})

    rl_tpu()
    emit("done", {"total_s": round(time.perf_counter() - T0, 1)})


if __name__ == "__main__":
    main()
