#!/bin/bash
# Serial on-chip campaign runner: probe4 -> probe5 -> official bench.
# One process, strictly serial = one chip claimant at a time, no
# process polling (pgrep-based waits deadlock against lingering
# wrapper shells whose cmdlines contain the script names).
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock -n 9 || exit 0     # another campaign runner already active
run_probe () {  # $1 = probe number
    local n=$1
    for i in $(seq 1 30); do
        echo "=== probe$n attempt $i $(date -u +%H:%M:%S) ===" >> "probe${n}_r04.err"
        python "tpu_probe${n}.py" >> "probe${n}_r04.out" 2>> "probe${n}_r04.err"
        # success needs a real MEASUREMENT stage, not just the canary:
        # probe2's canary passed while all nine MFU stages died on one
        # TypeError — that ledger must count as a retryable failure.
        if [ -f "TPU_PROBE${n}_r04.jsonl" ] \
                && grep -E '"stage": "(mfu|gen_scan|rl_|gen)"' "TPU_PROBE${n}_r04.jsonl" \
                   | grep -qv '"error"' \
                && ! grep -q abort "TPU_PROBE${n}_r04.jsonl"; then
            echo "=== probe$n results landed ===" >> "probe${n}_r04.err"
            return 0
        fi
        [ -f "TPU_PROBE${n}_r04.jsonl" ] && mv "TPU_PROBE${n}_r04.jsonl" "TPU_PROBE${n}_r04.abort.$i"
        sleep 90
    done
    return 1
}
run_probe 4
run_probe 5
# One fresh claim: the official bench with the updated defaults, so the
# round's BENCH capture reflects the best measured recipe.
python bench.py > BENCH_live_r04.json 2>> campaign_bench.err
echo "bench rc=$? $(date -u +%H:%M:%S)" >> campaign_bench.err
