#!/bin/bash
# probe16: accumulation depth + LHS at the new operating point + 4096-env pixel RL.
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9

ok16 () {
    [ -f TPU_PROBE16_r05.jsonl ] \
        && grep '"stage": "mfu"' TPU_PROBE16_r05.jsonl \
           | grep -v '"error"' | grep -q medium_m4
}

tries=0
while [ $tries -lt 8 ]; do
    tries=$((tries+1))
    echo "=== probe16 attempt $tries $(date -u +%H:%M:%S) ===" >> probe16_r05.err
    python tpu_probe16.py >> probe16_r05.out 2>> probe16_r05.err
    if ok16; then
        echo "=== probe16 landed $(date -u +%H:%M:%S) ===" >> probe16_r05.err
        break
    fi
    sleep 240
done
echo "stage K done $(date -u +%H:%M:%S)" >> campaign_r05.log
