"""num_returns="dynamic": tasks whose output count is decided at
runtime (reference: dynamic generators / ObjectRefGenerator).

The canonical use: a loader discovers how many shards a source splits
into; downstream tasks consume the shard refs without the whole dataset
ever landing in one process.
"""

import numpy as np

import ray_tpu


def main():
    ray_tpu.init(num_cpus=4)

    @ray_tpu.remote(num_returns="dynamic")
    def load_shards(n_rows, shard_rows):
        # shard count depends on the data — unknown at call time
        for start in range(0, n_rows, shard_rows):
            yield np.arange(start, min(start + shard_rows, n_rows),
                            dtype=np.float64)

    @ray_tpu.remote
    def shard_sum(shard):
        return float(shard.sum())

    gen = ray_tpu.get(load_shards.remote(1000, 256))
    print(f"loader produced {len(gen)} shards")
    totals = ray_tpu.get([shard_sum.remote(ref) for ref in gen])
    assert sum(totals) == sum(range(1000))
    print(f"sum over {len(totals)} shard tasks: {sum(totals):.0f}")
    ray_tpu.shutdown()
    print("EXAMPLE_OK dynamic_returns")


if __name__ == "__main__":
    main()
