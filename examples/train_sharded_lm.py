"""Train a sharded transformer with JaxTrainer: placement group ->
worker gang -> jax.distributed mesh -> pjit training loop."""

import jax
import jax.numpy as jnp
import optax

from ray_tpu.air import ScalingConfig, session
from ray_tpu.train import JaxTrainer


def train_loop(config):
    from ray_tpu.models import TransformerConfig, init_params, make_train_step
    from ray_tpu.parallel import FSDP_TP_RULES, batch_sharding, \
        pytree_shardings

    mesh = session.get_mesh()
    cfg = TransformerConfig.tiny(max_seq_len=32,
                                 attention_impl="reference",
                                 dtype=jnp.float32)
    params, axes = init_params(jax.random.PRNGKey(0), cfg)
    params = jax.device_put(params,
                            pytree_shardings(axes, mesh, FSDP_TP_RULES))
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)
    # accum_steps: microbatch the compiled step (activation memory at
    # batch/accum; Adam-moment traffic amortized — the r5 MFU lever)
    step = jax.jit(make_train_step(cfg, opt,
                                   accum_steps=config.get("accum", 1)))
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                           cfg.vocab_size),
        batch_sharding(mesh, FSDP_TP_RULES))
    # Mesh is its own context manager (works on jax 0.4 where
    # jax.set_mesh does not exist yet)
    with mesh:
        for i in range(config["steps"]):
            params, opt_state, metrics = step(params, opt_state,
                                              {"tokens": tokens})
            session.report({"step": i, "loss": float(metrics["loss"])})


def main():
    import ray_tpu
    ray_tpu.init(num_cpus=4)
    result = JaxTrainer(
        train_loop, train_loop_config={"steps": 3, "accum": 2},
        scaling_config=ScalingConfig(num_workers=2),
    ).fit()
    if result.error is not None \
            and "Multiprocess computations" in str(result.error):
        # this jaxlib's CPU backend cannot run cross-process collectives
        # (works on TPU and on newer jax CPU builds) — skip, don't fail
        print("SKIP train_sharded_lm: CPU backend lacks multiprocess "
              "collectives on this jaxlib")
        ray_tpu.shutdown()
        return
    print("final loss:", result.metrics["loss"])
    assert result.metrics["loss"] < 10
    print("EXAMPLE_OK train_sharded_lm")
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
