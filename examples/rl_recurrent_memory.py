"""Recurrent PPO (use_lstm) solving a memory task no feedforward policy
can: the cue is visible only at t=0, so the LSTM carry must hold it."""

from ray_tpu.rl import MemoryCue, PPOConfig


def main():
    algo = PPOConfig(env=MemoryCue, num_envs=32, rollout_length=64,
                     lr=3e-3, seed=0,
                     model={"use_lstm": True, "hidden": (32,),
                            "lstm_cell_size": 32}).build()
    for i in range(15):
        res = algo.train()
        if i % 5 == 0:
            print(f"iter {i}: reward={res['episode_reward_mean']:.2f} "
                  f"(memoryless ceiling 4.5, max 8.0)")
    assert res["episode_reward_mean"] > 6.5
    print("EXAMPLE_OK rl_recurrent_memory")


if __name__ == "__main__":
    main()
