"""AIR preprocessors end to end: fit on a Dataset, train, predict.

The workflow the reference documents for its preprocessor library
(`python/ray/data/preprocessors/` + train/base_trainer.py): a Chain
fits distributed statistics on the training Dataset, transforms every
split, rides the fitted state inside the result checkpoint, and
BatchPredictor applies the SAME transforms automatically at inference —
no train/serve skew.
"""

import numpy as np
import pandas as pd


def main():
    from sklearn.linear_model import LogisticRegression

    import ray_tpu
    from ray_tpu import data as rdata
    from ray_tpu.air import BatchPredictor
    from ray_tpu.data.preprocessors import (Chain, OneHotEncoder,
                                            SimpleImputer,
                                            StandardScaler)
    from ray_tpu.train.sklearn import SklearnTrainer

    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    rng = np.random.default_rng(0)
    n = 600
    income = rng.normal(60_000, 15_000, n)
    income[rng.random(n) < 0.1] = np.nan          # missing values
    segment = rng.choice(["a", "b", "c"], n)
    approved = ((np.nan_to_num(income, nan=60_000) > 55_000)
                & (segment != "c")).astype(float)
    df = pd.DataFrame({"income": income, "segment": segment,
                       "approved": approved})
    ds = rdata.from_pandas([df.iloc[:300], df.iloc[300:]])

    pp = Chain(SimpleImputer(["income"], strategy="mean"),
               StandardScaler(["income"]),
               OneHotEncoder(["segment"]))
    result = SklearnTrainer(
        LogisticRegression(), datasets={"train": ds},
        label_column="approved", preprocessor=pp).fit()
    print("fitted; checkpoint carries:",
          type(result.checkpoint.get_preprocessor()).__name__)

    def build(ckpt):
        import cloudpickle
        est = cloudpickle.loads(ckpt.to_dict()["estimator"])
        return lambda batch: est.predict(
            batch.drop(columns=["approved"]).to_numpy())

    test = pd.DataFrame({
        "income": [80_000.0, np.nan, 90_000.0],
        "segment": ["a", "b", "c"],
        "approved": [1.0, 1.0, 0.0]})
    preds = BatchPredictor(result.checkpoint, build).predict(
        rdata.from_pandas([test])).take_all()
    preds = np.asarray(preds, dtype=float).ravel()
    print("predictions (raw rows in, transforms applied inside):",
          preds)
    assert (preds == test["approved"].to_numpy()).all()
    ray_tpu.shutdown()
    print("EXAMPLE_OK air_preprocessors")


if __name__ == "__main__":
    main()
