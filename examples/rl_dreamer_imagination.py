"""Dreamer: learn a latent world model, train the policy purely in
imagination.  Short demo run (the full curve reaches ~113 on CartPole
around iteration 220) — run `python examples/rl_dreamer_imagination.py`."""

from ray_tpu.rl import CartPole, DreamerConfig


def main():
    algo = DreamerConfig(env=CartPole, num_envs=8, seq_len=12,
                         model_updates=2, ac_updates=2, seed=0).build()
    first = None
    for i in range(30):
        r = algo.train()
        if first is None and r["model_loss"] > 0:
            first = r["model_loss"]
        if i % 10 == 9:
            print(f"iter {i + 1}: model_loss {r['model_loss']:.2f} "
                  f"imagined_return {r['imagined_return']:.2f} "
                  f"reward {r['episode_reward_mean']:.1f}")
    assert r["model_loss"] < first, (first, r["model_loss"])
    print("EXAMPLE_OK rl_dreamer_imagination")


if __name__ == "__main__":
    main()
