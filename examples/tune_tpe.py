"""Hyperparameter search with the in-tree TPE searcher."""

import ray_tpu
from ray_tpu import tune
from ray_tpu.air import RunConfig, session
from ray_tpu.tune import TuneConfig, Tuner
from ray_tpu.tune.search import TPESearch


def objective(config):
    session.report({"loss": (config["x"] - 2.0) ** 2 + config["y"]})


def main():
    import tempfile
    ray_tpu.init(num_cpus=4)
    space = {"x": tune.uniform(-5, 5), "y": tune.choice([0.0, 1.0])}
    with tempfile.TemporaryDirectory() as storage:
        res = Tuner(
            objective, param_space=space,
            tune_config=TuneConfig(metric="loss", mode="min",
                                   num_samples=10,
                                   search_alg=TPESearch(space,
                                                        metric="loss",
                                                        mode="min")),
            run_config=RunConfig(name="tpe_demo", storage_path=storage),
        ).fit()
        best = res.get_best_result()
    print("best config:", best.metrics["config"],
          "loss:", best.metrics["loss"])
    print("EXAMPLE_OK tune_tpe")
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
