"""External-env RL: a pure-Python simulator trains the compiled learner.

The platform capability this shows (reference: rllib's
policy_server_input/policy_client examples): the simulator is NOT a
JaxEnv — it's plain numpy driven by its own loop, possibly in another
process or another machine — yet the learner's replay/update path stays
a single compiled XLA program.  The PolicyServerInput serves
epsilon-greedy actions over the framework's RPC plane and feeds the
transitions back into DQN's device-resident buffer.
"""

import threading
import time

import numpy as np


class TinySim:
    """A 1-D 'reach the target' toy in plain numpy: +1 for stepping
    toward the target, episode ends at the walls."""

    def __init__(self, seed=0):
        self.rng = np.random.default_rng(seed)

    def reset(self):
        self.pos = float(self.rng.uniform(-1, 1))
        self.target = float(self.rng.choice([-2.0, 2.0]))
        self.t = 0
        return np.asarray([self.pos, self.target], np.float32)

    def step(self, action):
        move = 0.25 if action == 1 else -0.25
        before = abs(self.target - self.pos)
        self.pos += move
        self.t += 1
        reward = 1.0 if abs(self.target - self.pos) < before else -1.0
        done = abs(self.pos) >= 2.0 or self.t >= 40
        return (np.asarray([self.pos, self.target], np.float32),
                reward, done)


def main():
    from ray_tpu.rl import DQNConfig, ExternalEnv, PolicyClient, \
        PolicyServerInput

    learner = DQNConfig(external_input=True, observation_size=2,
                        num_actions=2, ingest_chunk=32, learn_start=128,
                        eps_decay_steps=2_000, lr=2e-3, seed=0).build()
    server = PolicyServerInput(learner)
    learner.set_input_reader(server)

    class Runner(ExternalEnv):
        def run(self):
            sim = TinySim(seed=1)
            for _ in range(400):
                eid = self.client.start_episode()
                obs = sim.reset()
                done = False
                while not done:
                    a = self.client.get_action(eid, obs)
                    obs, r, done = sim.step(a)
                    self.client.log_returns(eid, r)
                self.client.end_episode(eid, obs)

    runner = Runner(PolicyClient(server.address))
    runner.start()
    reward = float("nan")
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        res = learner.train()
        if res["transitions_received"] < 16:
            time.sleep(0.05)
        reward = res["episode_reward_mean"]
        # optimal play earns ~+8/episode (one +1 per step to the wall,
        # 4-12 steps depending on spawn); random play nets ~0
        if np.isfinite(reward) and reward > 6.0:
            break
    print(f"learned from the external sim: episode_reward_mean="
          f"{reward:.1f} over {res['env_steps_total']} external steps")
    assert np.isfinite(reward) and reward > 4.0, reward
    runner.client.close()
    server.stop()
    print("EXAMPLE_OK rl_policy_server")


if __name__ == "__main__":
    main()
