"""Offline RL end to end: collect a mixed-quality dataset, train
discrete CQL on it (no environment interaction), deploy the greedy
policy and evaluate it online."""

import jax
import jax.numpy as jnp

from ray_tpu.rl import CQLConfig, collect_dataset
from ray_tpu.rl.env import CartPole


def behavior(obs, key):
    """Scripted demonstrator: decent controller 60% of the time,
    uniformly random otherwise."""
    good = (obs[2] + 0.5 * obs[3] > 0).astype(jnp.int32)
    rand = jax.random.randint(key, (), 0, 2)
    return jnp.where(jax.random.uniform(jax.random.fold_in(key, 1)) < 0.4,
                     rand, good)


def main():
    ds = collect_dataset(CartPole, behavior, n_steps=20_000, num_envs=32,
                         seed=0)
    algo = CQLConfig(env=CartPole, dataset=ds, epochs_per_iter=2,
                     cql_alpha=1.0, seed=0).build()
    for i in range(8):
        res = algo.train()
        if i % 4 == 0:
            print(f"iter {i}: cql_loss={res['cql_loss']:.3f} "
                  f"gap={res['cql_gap']:.3f}")
    ev = collect_dataset(CartPole, algo.action_fn(), n_steps=4000,
                         num_envs=16, seed=1)
    fails = float(ev["done"].sum())
    print(f"online eval: {fails:.0f} episode failures in 4000 steps "
          f"(behavior policy: ~160)")
    assert fails < 40
    print("EXAMPLE_OK rl_offline_cql")


if __name__ == "__main__":
    main()
