"""Core runtime quickstart: tasks, actors, objects, placement groups."""

import numpy as np

import ray_tpu


def main():
    ray_tpu.init(num_cpus=4)

    @ray_tpu.remote
    def square(x):
        return x * x

    print("tasks:", ray_tpu.get([square.remote(i) for i in range(5)]))

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote()
    ray_tpu.get([c.incr.remote() for _ in range(9)])
    print("actor count:", ray_tpu.get(c.incr.remote()))

    big = ray_tpu.put(np.arange(1_000_000))
    print("zero-copy sum:", int(ray_tpu.get(big).sum()))

    from ray_tpu.util import placement_group
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout_seconds=30), "placement group not ready"
    print("placement group ready: True")
    print("EXAMPLE_OK quickstart_core")
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
