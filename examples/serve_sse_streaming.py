"""Token streaming over one HTTP connection: SSE through the proxy.

The serving pattern for interactive generation (reference capability:
Serve's StreamingResponse): `POST /<route>/stream` makes the PROXY
drive the decode session and emit one server-sent event per token —
clients read tokens as they decode instead of polling per token, and
the replica's KV cache is released however the stream ends.
"""

import json

import ray_tpu
from ray_tpu import serve


def main():
    ray_tpu.init(num_cpus=4)
    serve.start()

    @serve.deployment
    class Generator:
        def __init__(self):
            import jax.numpy as jnp

            from ray_tpu.models import TransformerConfig
            from ray_tpu.serve.decode_session import DecodeSessionCore
            self.core = DecodeSessionCore(
                TransformerConfig.tiny(max_seq_len=64,
                                       attention_impl="reference",
                                       dtype=jnp.float32), max_len=64)

        def __call__(self, req):
            return self.core.handle(req)

    serve.run(Generator.bind(), name="llm")
    addr = serve.api.http_address()

    import requests
    tokens = []
    with requests.post(f"{addr}/llm/stream",
                       json={"prompt": [3, 1, 4, 1, 5],
                             "max_new_tokens": 8},
                       stream=True, timeout=180) as r:
        for line in r.iter_lines():
            if not line.startswith(b"data: "):
                continue
            body = line[len(b"data: "):]
            if body == b"[DONE]":
                break
            tokens.append(json.loads(body)["token"][0])
            print(f"token {len(tokens)}: {tokens[-1]}")
    assert len(tokens) == 8
    serve.shutdown()
    ray_tpu.shutdown()
    print("EXAMPLE_OK serve_sse_streaming")


if __name__ == "__main__":
    main()
