"""AlphaZero self-play on TicTacToe: fully-jitted array-tree MCTS.

The search tree is node-indexed tensors (the mctx design), every
simulation a bounded while_loop, and the whole self-play game batch one
vmapped program — run `python examples/rl_alpha_zero.py`."""

from ray_tpu.rl import AlphaZeroConfig


def main():
    az = AlphaZeroConfig(num_simulations=24, games_per_iter=32,
                         batch_size=64, seed=0).build()
    before = az.play_vs_random(n_games=12)
    for i in range(4):
        r = az.train()
        print(f"iter {i + 1}: loss {r['total_loss']:.3f} "
              f"p1-win {r['p1_win_rate']:.2f} "
              f"moves/game {r['moves_per_game']:.1f}")
    after = az.play_vs_random(n_games=12)
    print(f"vs random: before {before['az_win_rate']:.2f} "
          f"after {after['az_win_rate']:.2f} "
          f"(losses after: {after['random_win_rate']:.2f})")
    assert after["az_win_rate"] >= 0.5
    print("EXAMPLE_OK rl_alpha_zero")


if __name__ == "__main__":
    main()
