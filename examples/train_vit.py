"""Train a tiny ViT classifier — the encoder-side model family.

Same recipe as the LM quickstart: config → init (params + logical
axes) → jitted train step; the identical code pjit-shards over a mesh
via `pytree_shardings` (see tests/test_ops_models.py for the sharded
variant)."""

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models import (ViTConfig, init_vit_params,
                            make_vit_train_step, vit_forward)


def make_batch(key, n=64):
    """Synthetic 4-class bars task: class c puts a bright band at
    row/col block c (rows for even classes, columns for odd)."""
    kk, kl = jax.random.split(key)
    labels = jax.random.randint(kl, (n,), 0, 4)
    imgs = jnp.zeros((n, 16, 16, 1))
    for c in range(4):
        band = jnp.zeros((16, 16, 1))
        if c % 2 == 0:
            band = band.at[c * 4:(c * 4) + 4, :, :].set(1.0)
        else:
            band = band.at[:, c * 4:(c * 4) + 4, :].set(1.0)
        imgs = jnp.where((labels == c)[:, None, None, None], band[None],
                         imgs)
    imgs = imgs + 0.05 * jax.random.normal(kk, imgs.shape)
    return {"image": imgs, "label": labels}


def main():
    cfg = ViTConfig.tiny()
    params, _axes = init_vit_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adam(3e-3)
    step = jax.jit(make_vit_train_step(cfg, opt))
    opt_state = opt.init(params)
    for i in range(25):
        batch = make_batch(jax.random.PRNGKey(100 + i))
        params, opt_state, m = step(params, opt_state, batch)
        if i % 8 == 0:
            print(f"step {i}: loss={float(m['loss']):.3f} "
                  f"acc={float(m['accuracy']):.2f}")
    eval_batch = make_batch(jax.random.PRNGKey(999))
    logits = vit_forward(params, eval_batch["image"], cfg)
    acc = float((jnp.argmax(logits, -1) == eval_batch["label"]).mean())
    print(f"final eval accuracy: {acc:.2f}")
    assert acc > 0.7, acc
    print("EXAMPLE_OK train_vit")


if __name__ == "__main__":
    main()
