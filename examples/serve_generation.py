"""Serve KV-cache text generation behind the HTTP proxy."""

import requests

import ray_tpu
from ray_tpu import serve


def main():
    ray_tpu.init(num_cpus=4)
    serve.start()

    @serve.deployment
    class Generator:
        def __init__(self):
            import jax
            import jax.numpy as jnp

            from ray_tpu.models import TransformerConfig, init_params
            self.jnp = jnp
            self.cfg = TransformerConfig.tiny(max_seq_len=64,
                                              attention_impl="reference",
                                              dtype=jnp.float32)
            self.params, _ = init_params(jax.random.PRNGKey(0), self.cfg)

        def __call__(self, payload):
            from ray_tpu.models import generate
            prompt = self.jnp.asarray(payload["prompt"], self.jnp.int32)
            toks = generate(self.params, prompt, cfg=self.cfg,
                            max_new_tokens=int(payload.get("n", 8)))
            return {"tokens": toks.tolist()}

    serve.run(Generator.bind())
    out = requests.post(f"{serve.http_address()}/Generator",
                        json={"prompt": [[1, 2, 3]], "n": 5},
                        timeout=120).json()
    print("generated:", out["tokens"])
    assert len(out["tokens"][0]) == 5
    print("EXAMPLE_OK serve_generation")
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
