"""Train DQN on the pure-JAX CartPole (whole iteration jit-compiled)."""

from ray_tpu.rl import DQNConfig
from ray_tpu.rl.env import CartPole


def main():
    algo = DQNConfig(env=CartPole, num_envs=16, rollout_steps=32,
                     num_updates=64, eps_decay_steps=6000,
                     learn_start=512).build()
    for i in range(8):
        res = algo.train()
        print(f"iter {i}: reward={res['episode_reward_mean']:.1f} "
              f"steps/s={res['env_steps_per_s']:.0f}")
    print("EXAMPLE_OK rl_dqn_cartpole")


if __name__ == "__main__":
    main()
