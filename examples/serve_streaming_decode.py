"""Token-by-token serving: a stateful decode session on a Serve replica.

TTFT-style serving without waiting for the full completion: the replica
holds the KV cache between calls, so `start` pays one prefill and every
`next_token` call is a single decode step (the reference delegates this
to external engines; here it is the in-tree transformer runtime).
"""

import ray_tpu
from ray_tpu import serve


def main():
    ray_tpu.init(num_cpus=4)
    serve.start()

    @serve.deployment(max_concurrent_queries=4)
    class DecodeSession:
        def __init__(self):
            import jax
            import jax.numpy as jnp

            from ray_tpu.models import TransformerConfig, init_params
            self.jnp = jnp
            self.cfg = TransformerConfig.tiny(max_seq_len=64,
                                              attention_impl="reference",
                                              dtype=jnp.float32)
            self.params, _ = init_params(jax.random.PRNGKey(0), self.cfg)
            # the replica runs threaded (max_concurrent_queries > 1):
            # session state needs a lock
            import threading
            self._lock = threading.Lock()
            self.sessions = {}
            self._next = 0

        def __call__(self, req):
            from ray_tpu.models import decode_step, init_kv_cache, prefill
            jnp = self.jnp
            if req["op"] == "start":
                prompt = jnp.asarray(req["prompt"], jnp.int32)
                cache = init_kv_cache(self.cfg, prompt.shape[0], 64)
                logits, cache = prefill(self.params, prompt, self.cfg,
                                        cache)
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                with self._lock:
                    sid = self._next
                    self._next += 1
                    self.sessions[sid] = (cache, tok)
                return {"sid": sid, "token": tok.tolist()}
            with self._lock:
                cache, tok = self.sessions.pop(req["sid"])
            logits, cache = decode_step(self.params, tok, cache, self.cfg)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            with self._lock:
                self.sessions[req["sid"]] = (cache, tok)
            return {"token": tok.tolist()}

    handle = serve.run(DecodeSession.bind())
    out = handle.remote({"op": "start", "prompt": [[5, 6, 7]]}).result(
        timeout_s=180.0)
    sid = out["sid"]
    stream = [out["token"][0]]
    for _ in range(4):
        out = handle.remote({"op": "next", "sid": sid}).result(
            timeout_s=60.0)
        stream.append(out["token"][0])
    print("streamed tokens:", stream)
    assert len(stream) == 5
    print("EXAMPLE_OK serve_streaming_decode")
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
