"""Token-by-token serving: a stateful decode session on a Serve replica.

TTFT-style serving without waiting for the full completion: the replica
holds the KV cache between calls, so `start` pays one prefill and every
`next_token` call is a single decode step (the reference delegates this
to external engines; here it is the in-tree transformer runtime).
"""

import ray_tpu
from ray_tpu import serve


def main():
    ray_tpu.init(num_cpus=4)
    serve.start()

    @serve.deployment(max_concurrent_queries=4)
    class DecodeSession:
        def __init__(self):
            import jax.numpy as jnp

            from ray_tpu.models import TransformerConfig
            from ray_tpu.serve.decode_session import DecodeSessionCore
            # DecodeSessionCore jits prefill/decode once per replica and
            # locks the session table (the replica runs threaded)
            self.core = DecodeSessionCore(
                TransformerConfig.tiny(max_seq_len=64,
                                       attention_impl="reference",
                                       dtype=jnp.float32), max_len=64)

        def __call__(self, req):
            return self.core.handle(req)

    handle = serve.run(DecodeSession.bind())
    out = handle.remote({"op": "start", "prompt": [[5, 6, 7]]}).result(
        timeout_s=180.0)
    sid = out["sid"]
    stream = [out["token"][0]]
    for _ in range(4):
        out = handle.remote({"op": "next", "sid": sid}).result(
            timeout_s=60.0)
        stream.append(out["token"][0])
    print("streamed tokens:", stream)
    assert len(stream) == 5
    print("EXAMPLE_OK serve_streaming_decode")
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
