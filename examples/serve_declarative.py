"""Declarative Serve: deploy a YAML config, query it, read status back.

The GitOps-style flow (reference: `serve deploy` / `serve status`):
the application lives at an import path, the config names it with
overrides, and the cluster KV remembers what was applied.
"""

import json
import os
import sys
import tempfile

import requests

import ray_tpu
from ray_tpu import serve


def main():
    ray_tpu.init(num_cpus=4)
    # make this script importable as the config's import_path target
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    cfg_path = os.path.join(tempfile.mkdtemp(), "app.yaml")
    with open(cfg_path, "w") as f:
        f.write(
            "applications:\n"
            "  - name: adder\n"
            "    import_path: serve_declarative:adder_app\n"
            "    route_prefix: /add\n"
            "    deployments:\n"
            "      - name: Adder\n"
            "        num_replicas: 2\n"
            "        user_config:\n"
            "          increment: 10\n")

    handles = serve.apply_config(cfg_path)
    print("deployed:", sorted(handles))

    out = handles["adder"].remote({"x": 5}).result(timeout_s=60.0)
    print("handle call:", out)
    assert out == {"sum": 15}

    addr = serve.http_address()
    r = requests.post(f"{addr}/add", json={"x": 32}, timeout=30)
    print("HTTP call:", r.json())
    assert r.json() == {"sum": 42}

    status = serve.status()
    print("status:", json.dumps(status["applications"], indent=2))
    assert status["applications"]["adder"]["status"] == "RUNNING"
    assert serve.get_deployed_config()["applications"][0]["name"] == \
        "adder"

    serve.shutdown()
    ray_tpu.shutdown()
    print("EXAMPLE_OK serve_declarative")


@serve.deployment(num_replicas=1)
class Adder:
    def __init__(self):
        self.increment = 0

    def reconfigure(self, user_config):
        self.increment = user_config.get("increment", 0)

    def __call__(self, payload=None):
        return {"sum": (payload or {}).get("x", 0) + self.increment}


adder_app = Adder.bind()


if __name__ == "__main__":
    main()
