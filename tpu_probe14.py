"""Fourteenth staged on-chip probe — flash kernel block sweep at the
seq-2048 anomaly point.

bench.py's kernel micro (b1, 8 heads) times flash at 0.78x naive at
seq2048 with the headline's 1024x1024 blocks, while the TRAIN MFU at
the same seq shows flash 2.4x ahead (probe9: 0.3229 vs 0.1349) — the
micro is either block-tuned wrong for short seq or too small to cover
pallas grid overhead.  Two grids:

  * block sweep at (b1,h8,seq2048): q/k blocks in {512,1024,2048}
  * batch sweep: the same timing at b4 (the train step's operating
    point) for flash AND naive — if flash wins at b4, the micro's b1
    row was under-occupancy, not a kernel deficiency

Uses the shared probe_common harness.  Same discipline: ONE claim,
guarded stages, fsync'd ledger, never kill.
"""

import os
import time

from probe_common import ProbeLedger, enable_compile_cache

OUT = __file__.replace("tpu_probe14.py", "TPU_PROBE14_r05.jsonl")


def main() -> None:
    enable_compile_cache()
    led = ProbeLedger(OUT)
    if not led.claim_or_abort():
        return
    import jax
    import jax.numpy as jnp

    def chained_time(fn, q0, kb, vb, n=16) -> float:
        fnj = jax.jit(fn)
        out = fnj(q0, kb, vb)
        float(jnp.max(out))                   # compile + warmup; real sync
        t0 = time.perf_counter()
        for _ in range(n):
            out = fnj(out, kb, vb)
        float(jnp.max(out))
        return (time.perf_counter() - t0) / n

    def mk(batch, seq):
        ks = jax.random.split(jax.random.PRNGKey(seq + batch), 3)
        return [jax.random.normal(k, (batch, seq, 8, 64), jnp.bfloat16)
                for k in ks]

    def flash_time(batch, seq, bq, bk, tag):
        # block env vars are read at call time (ops.flash_attention
        # _env_block), so setting them between jits is enough
        os.environ["RAY_TPU_FLASH_BLOCK_Q"] = str(bq)
        os.environ["RAY_TPU_FLASH_BLOCK_K"] = str(bk)
        from ray_tpu.ops.flash_attention import flash_attention
        q, k, v = mk(batch, seq)
        t = chained_time(lambda *a: flash_attention(*a, causal=True),
                         q, k, v)
        led.emit("kernel", {"tag": tag, "batch": batch, "seq": seq,
                            "blocks": [bq, bk],
                            "ms": round(t * 1e3, 3)})
        return t

    def naive_time(batch, seq, tag):
        from ray_tpu.ops.attention import reference_attention
        q, k, v = mk(batch, seq)
        t = chained_time(
            lambda *a: reference_attention(*a, causal=True), q, k, v)
        led.emit("kernel", {"tag": tag, "batch": batch, "seq": seq,
                            "blocks": None, "ms": round(t * 1e3, 3)})
        return t

    # -- stage 1: block sweep at the anomaly point (b1, seq2048) ---------
    for bq, bk in ((512, 512), (1024, 512), (512, 1024), (2048, 1024),
                   (2048, 2048), (1024, 1024)):
        led.guarded(f"flash_b1_s2048_{bq}x{bk}")(flash_time)(
            1, 2048, bq, bk, f"flash_b1_s2048_{bq}x{bk}")
    led.guarded("naive_b1_s2048")(naive_time)(1, 2048, "naive_b1_s2048")

    # -- stage 2: representative batch (b4) at both seqs ------------------
    for seq in (2048, 8192):
        led.guarded(f"flash_b4_s{seq}")(flash_time)(
            4, seq, 1024, 1024, f"flash_b4_s{seq}_1024x1024")
        led.guarded(f"naive_b4_s{seq}")(naive_time)(4, seq,
                                                    f"naive_b4_s{seq}")

    led.emit("done", {"total_s": round(time.perf_counter() - led.t0, 1)})


if __name__ == "__main__":
    main()
