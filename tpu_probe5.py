"""Fifth staged on-chip probe — combine probe3's winners.

Probe3 found (v5e, gpt2-small, seq 1024): b16 + 1024x1024 flash blocks
= 0.3601 MFU; bf16 Adam-mu worth ~+0.01 at 1024x512 blocks; b32 OOM.
This probe tests the combinations probe3 didn't: the full stack
(b16 + 1024x1024 + bf16mu), b24, seq-2048 with the winning blocks, and
XLA's latency-hiding scheduler flag.

Same discipline: ONE claim, guarded stages, fsync'd ledger, never kill.
"""

import json
import os
import time
import traceback

T0 = time.perf_counter()
OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "TPU_PROBE5_r04.jsonl")


def log(msg: str) -> None:
    print(f"[probe5 {time.perf_counter() - T0:7.1f}s] {msg}", flush=True)


def emit(stage: str, payload: dict) -> None:
    rec = {"stage": stage, "t": round(time.perf_counter() - T0, 1)}
    rec.update(payload)
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())
    log(f"{stage}: {payload}")


def guarded(stage):
    def deco(fn):
        def run(*a, **kw):
            try:
                return fn(*a, **kw)
            except Exception as exc:
                emit(stage, {"error": repr(exc)[:300],
                             "tb": traceback.format_exc(limit=3)[-400:]})
                return None
        return run
    return deco


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_compile_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    from ray_tpu.models import (TransformerConfig, flops_per_token,
                                init_params, make_train_step)

    backend = jax.default_backend()
    dev = jax.devices()[0]
    emit("env", {"backend": backend,
                 "device": getattr(dev, "device_kind", "?")})
    if backend != "tpu":
        emit("abort", {"reason": f"backend={backend}, not tpu"})
        return
    peak = 197e12 if "v5" in dev.device_kind else 275e12

    @guarded("canary")
    def canary():
        x = jnp.ones((1024, 1024), jnp.bfloat16)
        jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
        emit("canary", {"ok": True})
        return True

    if canary() is None:
        emit("abort", {"reason": "canary failed; claim unhealthy"})
        return

    def measure_mfu(tag, cfg_kw, batch, steps=12, seq=1024,
                    blocks=(1024, 1024), mu_dtype=None):
        t_stage = time.perf_counter()
        os.environ["RAY_TPU_FLASH_BLOCK_Q"] = str(blocks[0])
        os.environ["RAY_TPU_FLASH_BLOCK_K"] = str(blocks[1])
        cfg = TransformerConfig.gpt2("small", loss_chunk=128,
                                     max_seq_len=max(1024, seq), **cfg_kw)
        params, _ = init_params(jax.random.PRNGKey(0), cfg)
        opt = optax.adamw(3e-4, weight_decay=0.1, mu_dtype=mu_dtype)
        opt_state = opt.init(params)
        step = jax.jit(make_train_step(cfg, opt), donate_argnums=(0, 1))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq),
                                    0, cfg.vocab_size)
        data = {"tokens": tokens}
        for _ in range(2):
            params, opt_state, m = step(params, opt_state, data)
        float(m["loss"])
        compile_s = time.perf_counter() - t_stage
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, m = step(params, opt_state, data)
        float(m["loss"])
        dt = time.perf_counter() - t0
        mfu = steps * batch * seq / dt * flops_per_token(cfg, seq) / peak
        if not (0.0 < mfu < 0.95):
            t0 = time.perf_counter()
            for _ in range(steps):
                params, opt_state, m = step(params, opt_state, data)
                float(m["loss"])
            dt = time.perf_counter() - t0
            mfu = steps * batch * seq / dt \
                * flops_per_token(cfg, seq) / peak
        emit("mfu", {"tag": tag, "batch": batch, "seq": seq,
                     "blocks": list(blocks), "mfu": round(mfu, 4),
                     "step_ms": round(1000 * dt / steps, 1),
                     "tok_s": round(steps * batch * seq / dt),
                     "compile_s": round(compile_s, 1)})
        del params, opt_state, step, tokens, data
        return mfu

    nr = dict(remat=False, norm_remat=True)
    bf16 = jnp.bfloat16
    for tag, kw, batch, seq, blocks, mu in (
            ("b16_kk_bf16mu", nr, 16, 1024, (1024, 1024), bf16),
            ("b24_kk", nr, 24, 1024, (1024, 1024), None),
            ("b24_kk_bf16mu", nr, 24, 1024, (1024, 1024), bf16),
            ("b8_seq2048_kk", nr, 8, 2048, (1024, 1024), None),
            ("b8_seq2048_kk_bf16mu", nr, 8, 2048, (1024, 1024), bf16),
    ):
        guarded(f"mfu:{tag}")(measure_mfu)(
            tag, kw, batch, seq=seq, blocks=blocks, mu_dtype=mu)

    # latency-hiding scheduler: compile-time flag, needs a fresh XLA
    # client to take effect — emit a marker so the runner script knows
    # to do the flagged rerun as a SEPARATE claim
    emit("done", {"total_s": round(time.perf_counter() - T0, 1)})


if __name__ == "__main__":
    main()
