#!/bin/bash
# Round-5 campaign, stage C: queued behind stages A (probe7/8/9) and B
# (probe10 + interim bench) on the serial flock; runs probe11 (llama-1b
# chunked-prefill TTFT — the bounded-compile answer to the round-4
# compile-helper killer).
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9

ok11 () {
    [ -f TPU_PROBE11_r05.jsonl ] \
        && grep '"stage": "mfu"' TPU_PROBE11_r05.jsonl \
           | grep -q chunked_prefill_ttft
}

tries=0
while [ $tries -lt 10 ]; do
    tries=$((tries+1))
    echo "=== probe11 attempt $tries $(date -u +%H:%M:%S) ===" >> probe11_r05.err
    python tpu_probe11.py >> probe11_r05.out 2>> probe11_r05.err
    if ok11; then
        echo "=== probe11 landed $(date -u +%H:%M:%S) ===" >> probe11_r05.err
        break
    fi
    if [ -f TPU_PROBE11_r05.jsonl ] && ! ok11; then
        mv TPU_PROBE11_r05.jsonl "TPU_PROBE11_r05.abort.$tries"
    fi
    sleep 240
done
echo "stage C done $(date -u +%H:%M:%S)" >> campaign_r05.log
