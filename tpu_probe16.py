"""Sixteenth staged on-chip probe — squeeze the new operating points.

probe15 crossed the 0.40 GPT-2 target (medium m4_a8 0.4175).  This
grid asks what's left on the table: deeper accumulation (a16), the
latency-hiding scheduler AT the accumulated operating point (the scan
epilogue + optimizer apply is exactly what LHS can overlap), small at
a8, and the pixel-RL env_chunk path at 4096 envs.

Uses the shared probe_common harness.  Same discipline: ONE claim,
guarded stages, fsync'd ledger, never kill.
"""

import time

from probe_common import ProbeLedger, enable_compile_cache, measure_mfu

OUT = __file__.replace("tpu_probe16.py", "TPU_PROBE16_r05.jsonl")
LHS_OPTS = {"xla_tpu_enable_latency_hiding_scheduler": "true"}


def main() -> None:
    enable_compile_cache()
    led = ProbeLedger(OUT)
    if not led.claim_or_abort():
        return
    import jax.numpy as jnp

    nr = dict(remat=False, norm_remat=True)
    bf16 = jnp.bfloat16
    for tag, preset, micro, accum, opts in (
            ("medium_m4_a16", "medium", 4, 16, None),
            ("medium_m4_a8_lhs", "medium", 4, 8, LHS_OPTS),
            ("small_m16_a8", "small", 16, 8, None),
    ):
        led.guarded(f"mfu:{tag}")(measure_mfu)(
            led, tag, nr, micro * accum, blocks=(1024, 1024),
            mu_dtype=bf16, preset=preset, accum_steps=accum,
            compiler_options=opts)

    def ppo_pong_4096():
        from ray_tpu.rl import PixelPong, PPOConfig
        algo = PPOConfig(env=PixelPong, num_envs=4096, rollout_length=64,
                         env_chunk=256, num_sgd_epochs=2,
                         num_minibatches=4, lr=3e-4, seed=0).build()
        t_c = time.perf_counter()
        algo.train()
        compile_s = time.perf_counter() - t_c
        t0 = time.perf_counter()
        steps = iters = 0
        while time.perf_counter() - t0 < 8.0 or iters < 3:
            res = algo.train()
            steps += res["env_steps_this_iter"]
            iters += 1
        dt = time.perf_counter() - t0
        led.emit("rl_ppo_pixel", {
            "env": "PixelPong(conv)", "num_envs": 4096, "rollout": 64,
            "env_chunk": 256, "env_steps_per_s": round(steps / dt, 1),
            "iters": iters, "compile_s": round(compile_s, 1),
            "reward": round(res["episode_reward_mean"], 2)})

    led.guarded("rl_ppo_pixel:4096")(ppo_pong_4096)()
    led.emit("done", {"total_s": round(time.perf_counter() - led.t0, 1)})


if __name__ == "__main__":
    main()
