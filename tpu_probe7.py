"""Seventh staged on-chip probe — the last MFU levers at the winning
recipe (b16, 1024x1024 flash blocks, bf16 Adam-mu = 0.3702 official):
loss_chunk sweep (128 default vs 256/512 — fewer, larger vocab-50k
matmuls per step) and XLA's latency-hiding scheduler (passed as
per-program compiler_options through the AOT compile path; pass
RAY_TPU_PROBE7_LHS=1 to run that variant — the runner invokes this
script twice).

Uses the shared probe_common harness.  Same discipline: ONE claim,
guarded stages, fsync'd ledger, never kill.
"""

import os

# Latency-hiding scheduler rides per-program compiler_options through
# the AOT compile path (probe_common.measure_mfu) — NOT XLA_FLAGS: the
# client-side flag parser in this jaxlib aborts on the unknown TPU flag
# (parse_flags_from_env fatal), and compilation happens in the remote
# helper anyway, which client env vars never reach.
LHS = os.environ.get("RAY_TPU_PROBE7_LHS") == "1"
LHS_OPTS = {"xla_tpu_enable_latency_hiding_scheduler": "true"}

import time  # noqa: E402

from probe_common import (ProbeLedger, enable_compile_cache,  # noqa: E402
                          measure_mfu)

OUT = __file__.replace("tpu_probe7.py", "TPU_PROBE7_r05.jsonl")


def main() -> None:
    enable_compile_cache()
    led = ProbeLedger(OUT)
    if not led.claim_or_abort():
        return
    import jax.numpy as jnp

    nr = dict(remat=False, norm_remat=True)
    bf16 = jnp.bfloat16
    suffix = "_lhs" if LHS else ""
    grid = ((f"b16_chunk256{suffix}", dict(nr, loss_chunk=256)),
            (f"b16_chunk512{suffix}", dict(nr, loss_chunk=512)))
    if LHS:  # the flagged rerun also re-measures the incumbent recipe
        grid = ((f"b16_chunk128{suffix}", nr),) + grid
    for tag, kw in grid:
        led.guarded(f"mfu:{tag}")(measure_mfu)(
            led, tag, kw, 16, blocks=(1024, 1024), mu_dtype=bf16,
            compiler_options=LHS_OPTS if LHS else None)

    led.emit("done", {"total_s": round(time.perf_counter() - led.t0, 1),
                      "lhs": LHS})


if __name__ == "__main__":
    main()
