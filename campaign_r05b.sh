#!/bin/bash
# Round-5 campaign, stage B: waits for the serial flock (stage A runs
# probe7/7lhs/8/9), then probe10 (non-composite Serve-on-chip TTFT)
# and an interim live bench capture as a hedge — the official
# report-time capture still happens on the final tree at round end.
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9

ok10 () {
    [ -f TPU_PROBE10_r05.jsonl ] \
        && grep '"stage": "serve_ttft"' TPU_PROBE10_r05.jsonl \
           | grep -qv '"error"'
}

tries=0
while [ $tries -lt 15 ]; do
    tries=$((tries+1))
    echo "=== probe10 attempt $tries $(date -u +%H:%M:%S) ===" >> probe10_r05.err
    python tpu_probe10.py >> probe10_r05.out 2>> probe10_r05.err
    if ok10; then
        echo "=== probe10 landed $(date -u +%H:%M:%S) ===" >> probe10_r05.err
        break
    fi
    if [ -f TPU_PROBE10_r05.jsonl ] && ! ok10; then
        mv TPU_PROBE10_r05.jsonl "TPU_PROBE10_r05.abort.$tries"
    fi
    sleep 240
done

echo "=== interim bench capture $(date -u +%H:%M:%S) ===" >> campaign_r05.log
python bench.py > BENCH_live_r05_interim.json 2>> campaign_r05.log
echo "interim bench rc=$? $(date -u +%H:%M:%S)" >> campaign_r05.log
