#!/bin/bash
# Final bounded chaser: retry probe8 (then probe9) until 19:30 UTC,
# then stop claiming entirely so the driver's end-of-round bench gets
# a quiet field. One claimant via the campaign flock.
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9
while [ "$(date -u +%H%M)" -lt 1930 ]; do
    python tpu_probe8.py >> probe8_r04.out 2>> probe8_r04.err
    if [ -f TPU_PROBE8_r04.jsonl ] && grep -q '"stage": "mfu"' TPU_PROBE8_r04.jsonl; then
        python tpu_probe9.py >> probe9_r04.out 2>> probe9_r04.err
        break
    fi
    [ -f TPU_PROBE8_r04.jsonl ] && mv TPU_PROBE8_r04.jsonl "TPU_PROBE8_r04.abort.$(date -u +%H%M)"
    sleep 60
done
echo "chaser exit $(date -u +%H:%M)" >> probe8_r04.err
