# Unified build entry points (the L0 role of the reference's bazel
# tree): native object store + transfer plane, C++ driver API, wheel.
PY ?= python

.PHONY: all native cpp wheel test bench serve-bench spec-bench obs \
	attr chaos drain failover spec elastic ha partition autoscale \
	autoscale-bench serve-breakdown profile lint lint-fast overload \
	diskfault containment clean

all: native cpp

native: ray_tpu/core/object_store/libtpustore.so

ray_tpu/core/object_store/libtpustore.so: \
		ray_tpu/core/object_store/store.cc \
		ray_tpu/core/object_store/transfer.cc
	g++ -O2 -shared -fPIC -pthread -o $@ $^

cpp:
	$(MAKE) -C ray_tpu/cpp

wheel: native
	$(PY) -m pip wheel --no-deps --no-build-isolation -w dist .

test:
	$(PY) -m pytest tests/ -q

# Observability suite: timeline/span propagation, runtime-metrics
# battery, structured events, plus the PR-10 flight-recorder layer —
# per-RPC attribution, metrics history, incident bundles, clock-offset
# timeline merge, metrics lint (all tier-1 — no `slow` markers).
obs:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_observability.py \
		tests/test_runtime_metrics.py tests/test_events.py \
		tests/test_control_plane_obs.py -q

# Per-RPC attribution snapshot: scripted task/actor wave, prints the
# controller handler table and appends it to the SCALE_r06 ledger
# (ROADMAP item 4's "before" evidence).
attr:
	JAX_PLATFORMS=cpu $(PY) bench.py --attr

# Chaos suite: seeded fault-injection units + all four end-to-end
# recovery scenarios (each runs twice with the same seeds — injection
# is deterministic).  Includes the `slow`-marked multi-process
# scenarios tier-1 skips.
chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos.py \
		tests/test_controller_ft.py -q

# Storage-fault suite (PR-18): filesystem chaos sites (WAL / spill /
# checkpoint / flight-recorder), WAL-poison self-fence -> standby
# promotion, spill CRC + ENOSPC backpressure, checkpoint keep-previous,
# disk-health watermarks, and the fn_lost re-registration path.
diskfault:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_diskfault.py -q

containment:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_containment.py -q

# Overload-protection suite (PR-17): priority RPC lanes, watermark
# state machine + admission shedding, credit flow control, bounded
# pubsub, kv-blob divert, and the tier-1 brownout soak.
overload:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_overload.py -q

# Drain suite: graceful-node-drain units + end-to-end phased
# evacuation, including the `slow` chaos variants (drain under serve
# traffic, injected evacuation failure -> lineage fallback).
drain:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_drain.py -q

# Failover suite: decode-stream failover — replay-journal/seq-dedupe
# units, teacher-forced resume parity, chaos mid-stream replica kill
# with byte-identical recovery, and the `slow` multi-node drain of a
# node hosting live streams (zero dropped sessions).
failover:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_serve_failover.py -q

# Elastic suite: unannounced-failure gang repair — crash-safe
# checkpoint registration, pubsub death/drain signal units, the hard
# node-kill acceptance scenario (fast repair, loss parity, ×2 seeds),
# and the `slow` chaos-abort / double-kill fallback cases.
elastic:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_elastic.py -q

# Controller HA suite: WAL CRC/replication units, split-brain epoch
# fencing, in-process promotion, the end-to-end kill-the-leader
# acceptance scenario (tables intact, in-flight wave completes, ×2
# seeds), chaos-severed replication -> bounded-lag async degrade, and
# the `slow` leader-death-mid-drain / mid-elastic-repair resumptions.
ha:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_controller_ha.py \
		tests/test_controller_ft.py -q

# Partition suite: gray-failure handling — connectivity-matrix fold
# units (asymmetric / controller-only / full partitions), the
# alternate-path fetch ladder, suspect/quarantine end to end
# (controller-link blackhole keeps the node SUSPECT, its actor
# survives, zero-restart rejoin ×2 seeds; grace exhaustion dies), and
# the `slow` asymmetric A↛B transfer partition under a task wave
# completing via the relay rung ×2 seeds.
partition:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_partition.py -q

# Spec suite: chunked-prefill admission + speculative decoding —
# verify-program exactness, chunk-boundary/admission parity, shared and
# adversarial (random) draft parity, chaos degrade-to-plain, resume
# into a speculating engine, program-shape dedup.
spec:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_serve_spec_decode.py -q

# Static analysis in one shot: the framework-invariant suite — all
# eight rules (PR-13: loop-blocking / thread-race / chaos-site /
# WAL-op / RPC-surface; PR-14: rpc-payload-contract / lock-order /
# wal-replay-determinism) in ONE invocation against the committed
# baseline — plus the PR-10 metrics lint.  Offline: no cluster, no
# JAX; both gate tier-1.
lint:
	$(PY) -m ray_tpu.scripts.cli lint
	$(PY) -m ray_tpu.scripts.cli metrics lint

# Pre-commit fast path: full registries, findings filtered to files
# git considers changed.
lint-fast:
	$(PY) -m ray_tpu.scripts.cli lint --changed

bench:
	$(PY) bench.py

# Serve decode benchmark: generation TTFT plus the continuous-batching
# streaming lane (1/4/8 concurrent SSE sessions; agg_tok_s and
# stream_ms_per_tok_p50) through the full proxy -> router -> replica
# path on the CPU harness.
serve-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --serve

# Chunked-prefill + speculative-decoding benchmark (engine level, CPU
# harness): spec-on vs spec-off ms/tok A/B with byte-identical-output
# assertion, and TTFT-under-load (long-prompt join into a saturated
# 8-session batch; stall inflicted on incumbents vs their steady chunk
# cadence).  Results merge into SERVE_BENCH.json detail.
spec-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --spec-bench

# Autoscale suite: pure policy units (trend/hysteresis/cooldown/SUSPECT
# down-weight/victim pick), prefix-trie units, engine shared-prefix
# admission parity, controller loop + chaos-dropped-decision retry,
# router prefix affinity, per-deployment metrics-history filter.
autoscale:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_serve_autoscale.py -q

# Bursty multi-tenant chat scenario (shared prefixes, sessions joining
# and leaving): replica-count-vs-load timeline + prefix-hit/cold TTFT,
# merged into SERVE_BENCH.json's `autoscale` block.
autoscale-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --autoscale-bench

# Serve attribution table (PR-16 data-plane flight instruments):
# streamed generation through the full path, reduced to per-phase
# ms/token (queue / admission / prefill / decode_dispatch /
# stream_drain) with the >=0.9 coverage bar; merges into
# SERVE_BENCH.json's `breakdown` block.
serve-breakdown:
	JAX_PLATFORMS=cpu $(PY) bench.py --serve-breakdown

# Dispatch-profiler / tracing suite: wrap-once shims, compile ledger,
# MFU table, per-request TTFT/ITL propagation, breakdown coverage,
# compile-storm + SLO-breach triggers.
profile:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_device_profile.py \
		tests/test_serve_breakdown.py -q

clean:
	rm -f ray_tpu/core/object_store/libtpustore.so dist/*.whl
	$(MAKE) -C ray_tpu/cpp clean
