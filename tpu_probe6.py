"""Sixth staged on-chip probe — pixel-env RL and MFU micro-levers.

The round-3 verdict called the RL north star "CartPole-weight"; probe3
fixed the substrate (285k env-steps/s ON the chip) and this probe
fixes the workload: PPO with the catalog's conv policy on PixelPong,
an Atari-class rendered-frame env, entirely on-device.  Also sweeps
loss_chunk (the last unmeasured MFU knob at the winning recipe).

Uses the shared probe_common harness.  Same discipline: ONE claim,
guarded stages, fsync'd ledger, never kill.
"""

import time

from probe_common import ProbeLedger, enable_compile_cache, measure_mfu

OUT = __file__.replace("tpu_probe6.py", "TPU_PROBE6_r04.jsonl")


def main() -> None:
    enable_compile_cache()
    led = ProbeLedger(OUT)
    if not led.claim_or_abort():
        return
    import jax
    import jax.numpy as jnp  # noqa: F401

    # ---- stage 1: conv-policy PPO on the pixel env ----------------------
    def ppo_pong(num_envs, rollout):
        from ray_tpu.rl import PixelPong, PPOConfig
        algo = PPOConfig(env=PixelPong, num_envs=num_envs,
                         rollout_length=rollout, num_sgd_epochs=2,
                         num_minibatches=4, lr=3e-4, seed=0).build()
        algo.train()                      # compile + warmup
        t0 = time.perf_counter()
        steps = 0
        iters = 0
        while time.perf_counter() - t0 < 8.0 or iters < 3:
            res = algo.train()
            steps += res["env_steps_this_iter"]
            iters += 1
        dt = time.perf_counter() - t0
        led.emit("rl_ppo_pixel", {
            "env": "PixelPong(conv)", "num_envs": num_envs,
            "rollout": rollout,
            "env_steps_per_s": round(steps / dt, 1), "iters": iters,
            "reward": round(res["episode_reward_mean"], 2)})

    for ne in (128, 512, 1024):
        led.guarded(f"rl_ppo_pixel:{ne}")(ppo_pong)(ne, 64)

    # ---- stage 2: loss_chunk sweep at the winning MFU recipe ------------
    nr = dict(remat=False, norm_remat=True)
    bf16 = jnp.bfloat16
    for tag, chunk in (("b16_chunk256", 256), ("b16_chunk512", 512)):
        led.guarded(f"mfu:{tag}")(measure_mfu)(
            led, tag, dict(nr, loss_chunk=chunk), 16,
            blocks=(1024, 1024), mu_dtype=bf16)

    led.emit("done", {"total_s": round(time.perf_counter() - led.t0, 1)})


if __name__ == "__main__":
    main()
