#!/bin/bash
# Round-5 serial on-chip campaign: probe7 (default + latency-hiding rerun)
# -> probe8 (gpt2-medium/large roofline) -> probe9 (long-context MFU).
# One process, strictly serial = one chip claimant at a time; no process
# polling (pgrep waits deadlock against lingering wrapper shells).  Each
# attempt is a fresh python start; while the grant is wedged attempts die
# fast in backend init and we sleep, which is also the wedge-cycling
# behavior that eventually frees it.
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock -n 9 || exit 0     # another campaign runner already active

ok () {  # $1 = ledger, $2 = required tag fragment
    [ -f "$1" ] && grep '"stage": "mfu"' "$1" | grep -v '"error"' \
        | grep -q "$2"
}

run () {  # $1 = script  $2 = ledger  $3 = logprefix  $4 = tag  $5 = env k=v
    local tries=0
    while [ $tries -lt 25 ]; do
        tries=$((tries+1))
        echo "=== $3 attempt $tries $(date -u +%H:%M:%S) ===" >> "$3_r05.err"
        if [ -n "$5" ]; then
            env "$5" python "$1" >> "$3_r05.out" 2>> "$3_r05.err"
        else
            python "$1" >> "$3_r05.out" 2>> "$3_r05.err"
        fi
        if ok "$2" "$4"; then
            echo "=== $3 results landed $(date -u +%H:%M:%S) ===" >> "$3_r05.err"
            return 0
        fi
        # move aside only a fully fruitless ledger — a later pass (e.g.
        # probe7's LHS rerun) appends to a ledger whose earlier rows are
        # good, and those must survive retries
        if [ -f "$2" ] && ! grep '"stage": "mfu"' "$2" | grep -qv '"error"'
        then
            mv "$2" "$2.abort.$3.$tries"
        fi
        sleep 240
    done
    return 1
}

run tpu_probe7.py TPU_PROBE7_r05.jsonl probe7 'chunk256' ''
run tpu_probe7.py TPU_PROBE7_r05.jsonl probe7lhs 'chunk128_lhs' 'RAY_TPU_PROBE7_LHS=1'
run tpu_probe8.py TPU_PROBE8_r05.jsonl probe8 'medium_b' ''
run tpu_probe9.py TPU_PROBE9_r05.jsonl probe9 'seq' ''
echo "campaign done $(date -u +%H:%M:%S)" >> campaign_r05.log
