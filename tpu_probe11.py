"""Eleventh staged on-chip probe — llama-1b prefill WITHOUT the
compile-helper killer (VERDICT r4 next-round #4).

Root cause of the round-4 failures: the whole-prompt llama-1b GQA flash
prefill compiles one program proportional to the full sequence; that
compile reliably killed the remote compile helper (~50 min hang, then
every later compile fails until the claim cycles).  The fix is not to
compile it: `prefill_chunked` (models/generate.py) extends the KV cache
through ONE small chunk program reused across the prompt — at most two
compiled shapes regardless of prompt length.

Stages: env/canary → chunked prefill TTFT at chunk 256 (prompt 1024)
→ prompt 2048 reusing the SAME compiled chunk program → per-token
decode.  All compiles are chunk-sized; nothing here has ever wedged the
helper class.
"""

import time

from probe_common import ProbeLedger, enable_compile_cache

OUT = __file__.replace("tpu_probe11.py", "TPU_PROBE11_r05.jsonl")


def main() -> None:
    enable_compile_cache()
    led = ProbeLedger(OUT)
    if not led.claim_or_abort():
        return
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import TransformerConfig, init_params
    from ray_tpu.models.generate import (decode_step, init_kv_cache,
                                         prefill_chunked)

    cfg = TransformerConfig.llama("1b", max_seq_len=2048)
    t0 = time.perf_counter()
    params, _ = init_params(jax.random.PRNGKey(0), cfg)
    jax.block_until_ready(params)
    led.emit("params", {"init_s": round(time.perf_counter() - t0, 1)})

    chunk = 256
    decode = jax.jit(decode_step, static_argnames=("cfg",))

    def sync(x) -> float:
        """Timing barrier that provably waits for device completion.

        Under the axon relay, ``jax.block_until_ready`` returns at
        remote ENQUEUE, not completion — the first probe11 capture
        reported 1.8 ms for a 1024-token llama-1b prefill (>1000
        TFLOP/s on a 197-TFLOP chip) and 0.09 ms/token decode (13 TB/s
        of weight reads).  A scalar host readback is a data dependency
        the relay cannot satisfy early.
        """
        return float(jnp.max(x))

    def ttft(prompt_len: int, tag: str) -> None:
        prompt = jax.random.randint(jax.random.PRNGKey(1),
                                    (1, prompt_len), 0, cfg.vocab_size)
        cache = init_kv_cache(cfg, 1, 2048)
        t0 = time.perf_counter()
        logits, cache = prefill_chunked(params, prompt, cfg, cache,
                                        chunk=chunk)
        sync(logits)
        first = time.perf_counter() - t0   # includes chunk compile once
        t0 = time.perf_counter()
        cache2 = init_kv_cache(cfg, 1, 2048)
        logits, cache2 = prefill_chunked(params, prompt, cfg, cache2,
                                         chunk=chunk)
        sync(logits)
        warm = time.perf_counter() - t0
        led.emit("mfu", {"tag": tag, "kind": "chunked_prefill_ttft",
                         "prompt_len": prompt_len, "chunk": chunk,
                         "synced": True,
                         "first_ms": round(first * 1e3, 1),
                         "warm_ttft_ms": round(warm * 1e3, 1)})
        # per-token decode from the built cache
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits2, cache2 = decode(params, tok, cache2, cfg=cfg)
        sync(logits2)                      # compile decode once
        steps = 16
        t0 = time.perf_counter()
        for _ in range(steps):
            tok = jnp.argmax(logits2, axis=-1).astype(jnp.int32)
            logits2, cache2 = decode(params, tok, cache2, cfg=cfg)
        sync(logits2)
        led.emit("mfu", {"tag": tag + "_decode", "kind": "decode",
                         "synced": True,
                         "ms_per_tok":
                             round((time.perf_counter() - t0) / steps
                                   * 1e3, 2)})

    led.guarded("ttft_1024")(ttft)(1024, "llama1b_seq1024")
    led.guarded("ttft_2048")(ttft)(2048, "llama1b_seq2048")
    led.emit("done", {"total_s": round(time.perf_counter() - led.t0, 1)})


if __name__ == "__main__":
    main()
