"""Parallelism-layer characterization on the virtual 8-device CPU mesh.

The VERDICT-r3 ask: even without multi-chip hardware, measure the
RELATIVE behavior of the parallel layer — PP bubble fraction vs
microbatch count, ring-vs-dense attention cost, EP all_to_all overhead —
so the next on-chip session has concrete predictions to check (the
reference's release/benchmarks publish the same style of scaling
tables).  Numbers here are CPU-mesh wall clock: collective cost models
ICI only in structure, not bandwidth, so the useful signal is the
TREND (bubble shrinking as 1/m, ring's overhead ratio, EP's dispatch
tax), not absolute ms.

Prints a markdown table + one JSON line; also writes
PARALLEL_BENCH.json for the round ledger.
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["RAY_TPU_DEVICE_BACKEND"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax                                                     # noqa: E402
import jax.numpy as jnp                                        # noqa: E402
import numpy as np                                             # noqa: E402

# sitecustomize registered the axon TPU plugin at interpreter start from
# the AMBIENT env (before this file's os.environ writes ran) — the
# config pin, not the env var, is what keeps backend discovery off the
# tunnelled chip (cf. tests/conftest.py).
jax.config.update("jax_platforms", "cpu")


def _time(fn, *args, iters=5):
    out = fn(*args)
    jax.block_until_ready(out)          # compile + warmup
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def bench_pipeline():
    """Step time vs n_micro at pp=4: bubble fraction (S-1)/(m+S-1)
    should show as wall-clock shrinking toward the m→inf asymptote."""
    from ray_tpu.models import (TransformerConfig, forward_with_aux,
                                init_params)
    from ray_tpu.parallel import MeshSpec, create_mesh

    rows = []
    stages = 4
    mesh = create_mesh(MeshSpec(dp=1, fsdp=1, pp=stages, sp=1, tp=2))
    for m in (1, 2, 4, 8, 16):
        cfg = TransformerConfig.tiny(
            n_layers=8, d_model=128, max_seq_len=64,
            attention_impl="reference", dtype=jnp.float32,
            pp_stages=stages, pp_microbatches=m)
        params, _ = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (16, 64), 0,
                                    cfg.vocab_size)
        with jax.set_mesh(mesh):
            fwd = jax.jit(lambda p, t, _cfg=cfg:
                          forward_with_aux(p, t, _cfg)[0])
            ms = _time(fwd, params, tokens) * 1e3
        bubble = (stages - 1) / (m + stages - 1)
        rows.append({"n_micro": m, "ms": round(ms, 1),
                     "bubble_theory": round(bubble, 3)})
        print(f"pp4 n_micro={m:<3d} {ms:8.1f} ms   "
              f"theoretical bubble {bubble:.3f}", file=sys.stderr)
    return rows


def bench_ring_vs_dense():
    """Ring attention (sp=8) vs single-device dense attention at
    growing sequence length; ring's win on real hardware is memory
    (seq/8 per chip) — on the CPU mesh the signal is compute parity
    and the per-step ppermute tax."""
    from ray_tpu.ops.attention import reference_attention
    from ray_tpu.ops.ring_attention import make_ring_attention
    from ray_tpu.parallel import MeshSpec, create_mesh

    mesh = create_mesh(MeshSpec(dp=1, fsdp=1, pp=1, sp=8, tp=1))
    ring = make_ring_attention(mesh)
    dense = jax.jit(lambda q, k, v:
                    reference_attention(q, k, v, causal=True))
    rows = []
    for seq in (1024, 4096, 8192):
        ks = jax.random.split(jax.random.PRNGKey(seq), 3)
        q, k, v = (jax.random.normal(kk, (1, seq, 8, 64), jnp.float32)
                   for kk in ks)
        t_ring = _time(ring, q, k, v, iters=3) * 1e3
        t_dense = _time(dense, q, k, v, iters=3) * 1e3
        rows.append({"seq": seq, "ring_ms": round(t_ring, 1),
                     "dense_ms": round(t_dense, 1),
                     "ratio": round(t_ring / t_dense, 2)})
        print(f"seq={seq:<6d} ring {t_ring:8.1f} ms   dense "
              f"{t_dense:8.1f} ms   ratio {t_ring / t_dense:.2f}",
              file=sys.stderr)
    return rows


def bench_moe_ep():
    """MoE ffn with experts sharded over ep=8 (GSPMD inserts
    all_to_alls) vs the SAME computation fully replicated: the delta is
    the dispatch/combine + all_to_all tax."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.ops.moe import moe_ffn
    from ray_tpu.parallel import MeshSpec, create_mesh

    mesh = create_mesh(MeshSpec(dp=1, fsdp=1, pp=1, sp=1, tp=1, ep=8))
    b, s, d, f, E = 8, 256, 128, 512, 8
    ks = jax.random.split(jax.random.PRNGKey(0), 5)
    y = jax.random.normal(ks[0], (b, s, d), jnp.float32)
    router = jax.random.normal(ks[1], (d, E)) * 0.1
    w_in = jax.random.normal(ks[2], (E, d, f)) * 0.1
    w_out = jax.random.normal(ks[3], (E, f, d)) * 0.1
    w_gate = jax.random.normal(ks[4], (E, d, f)) * 0.1

    def run(y, router, w_in, w_out, w_gate):
        out, _ = moe_ffn(y, router, w_in, w_out, w_gate, top_k=2,
                         capacity_factor=2.0)
        return out

    t_repl = _time(jax.jit(run), y, router, w_in, w_out, w_gate,
                   iters=3) * 1e3
    with jax.set_mesh(mesh):
        ep = NamedSharding(mesh, P("ep"))
        w_in_s, w_out_s, w_gate_s = (jax.device_put(w, ep)
                                     for w in (w_in, w_out, w_gate))
        t_ep = _time(jax.jit(run), y, router, w_in_s, w_out_s,
                     w_gate_s, iters=3) * 1e3
    print(f"moe E=8 top2: replicated {t_repl:.1f} ms   ep-sharded "
          f"{t_ep:.1f} ms   ratio {t_ep / t_repl:.2f}",
          file=sys.stderr)
    return {"replicated_ms": round(t_repl, 1),
            "ep8_ms": round(t_ep, 1),
            "ratio": round(t_ep / t_repl, 2)}


def main():
    result = {
        "metric": "parallel_layer_characterization",
        "value": 1.0, "unit": "suite", "vs_baseline": 1.0,
        "detail": {
            "mesh": "8-device virtual CPU",
            "pipeline_pp4": bench_pipeline(),
            "ring_vs_dense_sp8": bench_ring_vs_dense(),
            "moe_ep8": bench_moe_ep(),
        },
    }
    print(json.dumps(result))
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(
                __file__)), "PARALLEL_BENCH.json"), "w") as f:
            json.dump(result, f, indent=1)
    except OSError:
        pass


if __name__ == "__main__":
    main()
