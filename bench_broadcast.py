"""Object-broadcast benchmark across local nodelets.

Fills the reference's release-benchmark row "broadcast a 1 GiB object"
(`/root/reference/release/benchmarks/README.md:16-19` — 1 GiB to 50+
nodes) at this harness's scale: one driver `put` on the head node's
shm store, one actor pinned to each OTHER nodelet `get`s it, so every
byte crosses the C++ transfer plane (store-to-store TCP,
`ray_tpu/core/object_store/transfer.cc`) exactly once per receiving
node.  All nodelets share this machine, so the number is a
single-machine upper bound on the per-link plane, not a network claim
— the useful signals are scaling shape (per-node bandwidth as receiver
count grows) and the zero-copy path holding up at GiB sizes.

Prints a markdown table + one JSON line; writes BROADCAST_BENCH.json.
"""

import json
import os
import sys
import time

os.environ.setdefault("RAY_TPU_DASHBOARD_AGENT", "0")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import numpy as np                                             # noqa: E402

import ray_tpu                                                 # noqa: E402
from ray_tpu.cluster_utils import Cluster                      # noqa: E402
from ray_tpu.util.scheduling_strategies import (               # noqa: E402
    NodeAffinitySchedulingStrategy)


@ray_tpu.remote
class Receiver:
    def fetch(self, wrapped_ref):
        # actor-side get: pulls the object into THIS node's store via
        # the transfer plane, returns (first+last byte, elapsed seconds).
        # The ref rides NESTED in a list — a top-level ref arg would be
        # auto-resolved (and transferred) before the timer starts.
        t0 = time.perf_counter()
        arr = ray_tpu.get(wrapped_ref[0], timeout=300.0)
        dt = time.perf_counter() - t0
        return int(arr[0]), int(arr[-1]), dt


def bench(n_receivers: int, size_mb: int, cluster: Cluster) -> dict:
    size = size_mb * 1024 * 1024
    payload = np.arange(size, dtype=np.uint8)  # wraps mod 256; non-zero
    ref = ray_tpu.put(payload)
    receivers = [
        Receiver.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=cluster.nodes[i + 1].node_id)).remote()
        for i in range(n_receivers)]
    # readiness barrier so spawn time stays out of the bandwidth number
    ray_tpu.get([r.fetch.remote([ray_tpu.put(np.zeros(1, np.uint8))])
                 for r in receivers], timeout=120.0)
    t0 = time.perf_counter()
    out = ray_tpu.get([r.fetch.remote([ref]) for r in receivers],
                      timeout=600.0)
    wall = time.perf_counter() - t0
    for first, last, _ in out:
        assert first == 0 and last == (size - 1) % 256, "payload corrupt"
    per_node = [dt for _, _, dt in out]
    total_gb = n_receivers * size / 1e9
    row = {
        "receivers": n_receivers, "size_mb": size_mb,
        "wall_s": round(wall, 3),
        "aggregate_GBps": round(total_gb / wall, 2),
        "per_node_GBps_median": round(
            size / 1e9 / sorted(per_node)[len(per_node) // 2], 2),
    }
    for r in receivers:
        ray_tpu.kill(r)
    del ref
    return row


def main() -> None:
    n_workers = 4
    cluster = Cluster()
    # head (driver attach) + workers; stores sized for the 1 GiB row
    for _ in range(n_workers + 1):
        cluster.add_node(num_cpus=2,
                         object_store_memory=1536 * 1024 * 1024)
    cluster.connect(cluster.nodes[0])
    rows = []
    try:
        for n_recv, size_mb in ((1, 64), (4, 64), (1, 1024), (4, 1024)):
            rows.append(bench(n_recv, size_mb, cluster))
            print(f"# {rows[-1]}", flush=True)
    finally:
        cluster.shutdown()
    print("\n| receivers | size | wall s | aggregate GB/s | per-node GB/s |")
    print("|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['receivers']} | {r['size_mb']} MiB | {r['wall_s']} "
              f"| {r['aggregate_GBps']} | {r['per_node_GBps_median']} |")
    result = {
        "metric": "broadcast_1gib_4node_aggregate_GBps",
        "value": rows[-1]["aggregate_GBps"], "unit": "GB/s",
        # reference row is feasibility at 50 nodes, not a bandwidth
        # number; vs_baseline 1.0 = the capability row is filled
        "vs_baseline": 1.0,
        "detail": {"rows": rows, "plane": "store-to-store TCP "
                   "(transfer.cc), single machine"},
    }
    print(json.dumps(result))
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BROADCAST_BENCH.json"), "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    sys.exit(main())
