#!/bin/bash
# stage V: probe21 (scanned-generation honest decode) then the final
# validation bench on the count-weighted-accum tree.
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9

ok21b () {
    [ -f TPU_PROBE21_r05.jsonl ] \
        && grep '"stage": "mfu"' TPU_PROBE21_r05.jsonl \
           | grep -v '"error"' | grep -qv ERRNEVER
}

tries=0
while [ $tries -lt 6 ]; do
    tries=$((tries+1))
    echo "=== probe21 attempt $tries $(date -u +%H:%M:%S) ===" >> probe21_r05.err
    python tpu_probe21.py >> probe21_r05.out 2>> probe21_r05.err
    if ok21b; then
        echo "=== probe21 landed $(date -u +%H:%M:%S) ===" >> probe21_r05.err
        break
    fi
    sleep 240
done

echo "=== stage V bench $(date -u +%H:%M:%S) ===" >> campaign_r05.log
python bench.py > BENCH_live_r05_interim.json 2>> campaign_r05.log
echo "stage V bench rc=$? $(date -u +%H:%M:%S)" >> campaign_r05.log
