"""Seventeenth staged on-chip probe — streamed decode through Serve.

probe10 measured decode at 70 ms/token through per-request polling
(each token = one HTTP POST), while the chip-side decode dispatch is
~17 ms/token (probe11) — the difference is per-request serving-path
overhead paid per token.  SSE streaming (`POST /<route>/stream`, one
request, proxy-driven decode loop, one server-sent event per token) is
the serving answer; this probe measures its per-token inter-arrival on
the same on-chip gpt2-small replica as probe10.

Claim discipline: replica is the only chip claimant; flock serializes.
"""

import os
import time

os.environ.setdefault("RAY_TPU_WORKER_SHUTDOWN_GRACE_S", "30")
os.environ.setdefault("RAY_TPU_TPU_AUTODETECT", "0")

from probe_common import ProbeLedger  # noqa: E402

OUT = __file__.replace("tpu_probe17.py", "TPU_PROBE17_r05.jsonl")


def main() -> None:
    led = ProbeLedger(OUT)
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4)
    serve.start()

    @serve.deployment(max_concurrent_queries=4)
    class Generator:
        def __init__(self):
            import jax
            import jax.numpy as jnp

            from ray_tpu.models import TransformerConfig
            from ray_tpu.serve.decode_session import DecodeSessionCore
            self.backend = jax.default_backend()
            self.device = getattr(jax.devices()[0], "device_kind", "?")
            dtype = jnp.bfloat16 if self.backend == "tpu" else jnp.float32
            self.core = DecodeSessionCore(
                TransformerConfig.gpt2("small", max_seq_len=512,
                                       dtype=dtype),
                max_len=512)

        def __call__(self, req):
            if req.get("op") == "env":
                return {"backend": self.backend, "device": self.device}
            return self.core.handle(req)

    import numpy as np
    import requests
    serve.run(Generator.bind(), name="generate")
    addr = serve.api.http_address()
    http = requests.Session()

    env = http.post(f"{addr}/generate", json={"op": "env"},
                    timeout=600).json()
    led.emit("env", env)
    if env.get("backend") != "tpu":
        led.emit("abort", {"reason": f"replica backend={env.get('backend')}"})
        _teardown(serve, ray_tpu)
        return

    prompt_len, new_tokens = 256, 24

    def stream_session(i: int):
        prompt = [(7 * i + j) % 250 for j in range(prompt_len)]
        arrivals = []
        t0 = time.perf_counter()
        with http.post(f"{addr}/generate/stream",
                       json={"prompt": prompt,
                             "max_new_tokens": new_tokens},
                       stream=True, timeout=900) as r:
            r.raise_for_status()
            for line in r.iter_lines():
                if not line.startswith(b"data: "):
                    continue
                if line[len(b"data: "):] == b"[DONE]":
                    break
                arrivals.append(time.perf_counter())
        if not arrivals:
            raise RuntimeError("stream yielded no token events")
        ttft = arrivals[0] - t0
        gaps = np.diff(arrivals)
        return ttft, gaps

    # teardown MUST run however measurement ends — a leaked replica
    # keeps the chip claimed and every campaign retry then fails
    # against it (the other probes' guarded-stage equivalent)
    try:
        led.log("warmup (compiles prefill+decode on chip)")
        t0 = time.perf_counter()
        stream_session(0)
        led.emit("warmup",
                 {"compile_s": round(time.perf_counter() - t0, 1)})

        ttfts, gaps = [], []
        for i in range(1, 9):
            ttft, g = stream_session(i)
            ttfts.append(ttft)
            gaps.extend(g.tolist())
        led.emit("serve_stream", {
            "p50_ttft_ms": round(float(np.percentile(ttfts, 50)) * 1e3,
                                 2),
            "stream_ms_per_tok_p50":
                round(float(np.percentile(gaps, 50)) * 1e3, 2),
            "stream_tok_s":
                round(1.0 / max(float(np.mean(gaps)), 1e-9), 1),
            "sessions": 8, "prompt_len": prompt_len,
            "new_tokens": new_tokens,
            "path":
                "http SSE stream->proxy-driven decode(replica ON CHIP)",
            "model": "gpt2-small bf16 seq512"})
    except Exception as exc:
        led.emit("serve_stream", {"error": repr(exc)[:300]})
    finally:
        _teardown(serve, ray_tpu)
    led.emit("done", {"teardown": "graceful"})


def _teardown(serve, ray_tpu) -> None:
    serve.shutdown()
    time.sleep(5.0)
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
