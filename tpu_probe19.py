"""Nineteenth staged on-chip probe — scanned one-program generation
with honest barriers.

probe11's 17.3 ms/token decode is per-dispatch: every token pays a
relay round trip.  The framework's `generate` (one compiled program:
prefill + `lax.scan` of decode_step) amortizes the relay over the
whole generation — this probe measures its per-token cost with the
scalar-readback barrier (r4's probe4 measured the same path at
~2.4 ms/step but through the enqueue-returning block_until_ready, so
that number was the relay floor, not the chip).

Prompts stay SHORT (64-256) so the prefill grid inside the program is
small — whole-prompt llama GQA flash prefill at >=512 was the r4
compile killer (chunked prefill is the serving answer; this probe is
about the scanned DECODE).
"""

import time

from probe_common import ProbeLedger, enable_compile_cache

OUT = __file__.replace("tpu_probe19.py", "TPU_PROBE19_r05.jsonl")


def main() -> None:
    enable_compile_cache()
    led = ProbeLedger(OUT)
    if not led.claim_or_abort():
        return
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import TransformerConfig, init_params
    from ray_tpu.models.generate import generate

    def gen_stage(tag, cfg, batch, prompt_len, new_tokens):
        params, _ = init_params(jax.random.PRNGKey(0), cfg)
        jax.block_until_ready(params)
        prompt = jax.random.randint(jax.random.PRNGKey(1),
                                    (batch, prompt_len), 0,
                                    cfg.vocab_size)
        t0 = time.perf_counter()
        toks = generate(params, prompt, cfg=cfg,
                        max_new_tokens=new_tokens,
                        max_len=prompt_len + new_tokens)
        float(jnp.sum(toks))              # honest completion barrier
        first = time.perf_counter() - t0
        t0 = time.perf_counter()
        toks = generate(params, prompt, cfg=cfg,
                        max_new_tokens=new_tokens,
                        max_len=prompt_len + new_tokens)
        float(jnp.sum(toks))
        warm = time.perf_counter() - t0
        led.emit("gen", {"tag": tag, "batch": batch,
                         "prompt_len": prompt_len,
                         "new_tokens": new_tokens, "synced": True,
                         "first_s": round(first, 1),
                         "warm_ms": round(warm * 1e3, 1),
                         "ms_per_tok": round(warm * 1e3 / new_tokens, 2),
                         "agg_tok_s":
                             round(batch * new_tokens / warm, 1)})

    small = TransformerConfig.gpt2("small", max_seq_len=512)
    led.guarded("gen:gpt2s_b1")(gen_stage)(
        "gpt2s_b1_scan", small, 1, 256, 64)
    llama = TransformerConfig.llama(
        "1b", max_seq_len=256, param_dtype=jnp.bfloat16)
    led.guarded("gen:llama1b_b1")(gen_stage)(
        "llama1b_b1_scan", llama, 1, 64, 64)
    led.guarded("gen:llama1b_b8")(gen_stage)(
        "llama1b_b8_scan", llama, 8, 64, 64)

    led.emit("done", {"total_s": round(time.perf_counter() - led.t0, 1)})


if __name__ == "__main__":
    main()
