#!/bin/bash
# stage P: probe19 (scanned-generation honest decode) then the final
# validation bench on the count-weighted-accum tree.
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9

ok19 () {
    [ -f TPU_PROBE19_r05.jsonl ] \
        && grep '"stage": "gen"' TPU_PROBE19_r05.jsonl \
           | grep -v '"error"' | grep -q scan
}

tries=0
while [ $tries -lt 6 ]; do
    tries=$((tries+1))
    echo "=== probe19 attempt $tries $(date -u +%H:%M:%S) ===" >> probe19_r05.err
    python tpu_probe19.py >> probe19_r05.out 2>> probe19_r05.err
    if ok19; then
        echo "=== probe19 landed $(date -u +%H:%M:%S) ===" >> probe19_r05.err
        break
    fi
    sleep 240
done

echo "=== stage P bench $(date -u +%H:%M:%S) ===" >> campaign_r05.log
python bench.py > BENCH_live_r05_interim.json 2>> campaign_r05.log
echo "stage P bench rc=$? $(date -u +%H:%M:%S)" >> campaign_r05.log
