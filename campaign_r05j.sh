#!/bin/bash
# Round-5 campaign, stage J: probe11 rerun with the honest completion
# barrier (scalar host readback; "synced": true rows) — the first
# capture timed remote ENQUEUE, not execution.
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9

ok11b () {
    [ -f TPU_PROBE11_r05.jsonl ] \
        && grep '"synced": true' TPU_PROBE11_r05.jsonl \
           | grep -v '"error"' | grep -q chunked_prefill_ttft
}

tries=0
while [ $tries -lt 8 ]; do
    tries=$((tries+1))
    echo "=== probe11sync attempt $tries $(date -u +%H:%M:%S) ===" >> probe11_r05.err
    python tpu_probe11.py >> probe11_r05.out 2>> probe11_r05.err
    if ok11b; then
        echo "=== probe11sync landed $(date -u +%H:%M:%S) ===" >> probe11_r05.err
        break
    fi
    sleep 240
done
echo "stage J done $(date -u +%H:%M:%S)" >> campaign_r05.log
