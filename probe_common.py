"""Shared harness for the staged on-chip probes (tpu_probe*.py).

Extracted after round 4: the emit/guarded/measure_mfu bodies were
copy-pasted across four probe scripts, and a bug in one copy (the
gpt2() max_seq_len collision) cost a chip window while the other
copies had diverged.  New probes: ``from probe_common import
ProbeLedger, measure_mfu`` and keep the per-probe file to just its
stage grid.

Discipline (learned rounds 3-4, encoded here):
  * ONE claim per process; never kill a TPU run mid-compile.
  * Every stage guarded — one bad stage must not sink the claim.
  * Every result fsync'd to the ledger immediately.
  * Canary (tiny matmul) before committing the claim to big compiles.
"""

import json
import os
import time
import traceback

from bench import _peak_flops


class ProbeLedger:
    """fsync'd JSONL ledger + guarded-stage decorator for one probe."""

    def __init__(self, out_path: str):
        self.t0 = time.perf_counter()
        self.out = out_path

    def log(self, msg: str) -> None:
        print(f"[probe {time.perf_counter() - self.t0:7.1f}s] {msg}",
              flush=True)

    def emit(self, stage: str, payload: dict) -> None:
        rec = {"stage": stage, "t": round(time.perf_counter() - self.t0, 1)}
        rec.update(payload)
        with open(self.out, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self.log(f"{stage}: {payload}")

    def guarded(self, stage: str):
        def deco(fn):
            def run(*a, **kw):
                try:
                    return fn(*a, **kw)
                except Exception as exc:
                    self.emit(stage, {
                        "error": repr(exc)[:300],
                        "tb": traceback.format_exc(limit=3)[-400:]})
                    return None
            return run
        return deco

    def claim_or_abort(self) -> bool:
        """env + canary stages; False means don't burn the claim."""
        import jax
        import jax.numpy as jnp
        backend = jax.default_backend()
        dev = jax.devices()[0]
        self.emit("env", {"backend": backend,
                          "device": getattr(dev, "device_kind", "?")})
        if backend != "tpu":
            self.emit("abort", {"reason": f"backend={backend}, not tpu"})
            return False

        def canary():
            x = jnp.ones((1024, 1024), jnp.bfloat16)
            jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
            self.emit("canary", {"ok": True})
            return True

        if self.guarded("canary")(canary)() is None:
            self.emit("abort", {"reason": "canary failed; claim unhealthy"})
            return False
        return True


def enable_compile_cache() -> None:
    import jax
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_compile_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass


def measure_mfu(ledger: ProbeLedger, tag: str, cfg_kw: dict, batch: int,
                steps: int = 12, seq: int = 1024,
                blocks=(1024, 1024), mu_dtype=None,
                preset: str = "small",
                compiler_options: dict | None = None,
                accum_steps: int = 1) -> float:
    """GPT-2 train-step MFU at the given recipe (``preset`` picks the
    size; default small = the BASELINE workload); emits an "mfu" stage
    record.  Peak FLOPs via bench._peak_flops (device-kind table,
    longest-prefix matched — the probes' old `"v5" in kind` guess
    mis-rated v5p/v6e).

    ``compiler_options`` go through the AOT ``lower().compile()`` path —
    the only channel that reaches the compiler when compilation happens
    in the remote helper (client-side XLA_FLAGS either never arrive or,
    worse, hit the local parser as unknown flags and abort the
    process)."""
    import jax
    import optax

    from ray_tpu.models import (TransformerConfig, flops_per_token,
                                init_params, make_train_step)
    t_stage = time.perf_counter()
    os.environ["RAY_TPU_FLASH_BLOCK_Q"] = str(blocks[0])
    os.environ["RAY_TPU_FLASH_BLOCK_K"] = str(blocks[1])
    cfg_kw = dict(cfg_kw)
    cfg = TransformerConfig.gpt2(
        preset, loss_chunk=cfg_kw.pop("loss_chunk", 128),
        max_seq_len=max(1024, seq), **cfg_kw)
    params, _ = init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(3e-4, weight_decay=0.1, mu_dtype=mu_dtype)
    opt_state = opt.init(params)
    step = jax.jit(make_train_step(cfg, opt, accum_steps=accum_steps),
                   donate_argnums=(0, 1))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq),
                                0, cfg.vocab_size)
    data = {"tokens": tokens}
    if compiler_options:
        step = step.lower(params, opt_state, data).compile(
            compiler_options=compiler_options)
    for _ in range(2):
        params, opt_state, m = step(params, opt_state, data)
    float(m["loss"])
    compile_s = time.perf_counter() - t_stage
    peak = _peak_flops(jax.devices()[0])
    from bench import timed_mfu_loop
    mfu, dt, params, opt_state = timed_mfu_loop(
        step, params, opt_state, data, steps, batch * seq,
        flops_per_token(cfg, seq), peak)
    ledger.emit("mfu", {"tag": tag, "model": f"gpt2-{preset}",
                        "batch": batch, "seq": seq,
                        "accum": accum_steps,
                        "blocks": list(blocks), "mfu": round(mfu, 4),
                        "step_ms": round(1000 * dt / steps, 1),
                        "tok_s": round(steps * batch * seq / dt),
                        "compile_s": round(compile_s, 1)})
    return mfu
