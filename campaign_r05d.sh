#!/bin/bash
# Round-5 campaign, stage D: queued behind stages A/B/C on the serial
# flock; runs probe12 (pixel-env PPO past the 128-env compile ceiling
# via PPOConfig.env_chunk — bounded-compile rollouts).
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9

# Gate on the 512-env row (the headline ask): 1024/2048 rows are
# guarded extras whose legitimate OOM/ceiling errors should NOT force a
# rerun that moves a good ledger aside.
ok12 () {
    [ -f TPU_PROBE12_r05.jsonl ] \
        && grep '"stage": "rl_ppo_pixel"' TPU_PROBE12_r05.jsonl \
           | grep -v '"error"' | grep -q '"num_envs": 512'
}

tries=0
while [ $tries -lt 10 ]; do
    tries=$((tries+1))
    echo "=== probe12 attempt $tries $(date -u +%H:%M:%S) ===" >> probe12_r05.err
    python tpu_probe12.py >> probe12_r05.out 2>> probe12_r05.err
    if ok12; then
        echo "=== probe12 landed $(date -u +%H:%M:%S) ===" >> probe12_r05.err
        break
    fi
    if [ -f TPU_PROBE12_r05.jsonl ] && ! ok12; then
        mv TPU_PROBE12_r05.jsonl "TPU_PROBE12_r05.abort.$tries"
    fi
    sleep 240
done
echo "stage D done $(date -u +%H:%M:%S)" >> campaign_r05.log
