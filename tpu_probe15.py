"""Fifteenth staged on-chip probe — gradient accumulation as the last
single-chip MFU lever.

probe13 capped the batch-bound ceiling at medium_b5 = 0.3865 (b6 OOMs).
Accumulation changes the trade: activation memory scales with the
MICRObatch while the Adam-moment read/write traffic (~GBs/update)
amortizes over ``accum`` x more tokens — per-token model FLOPs (the
MFU numerator) unchanged.  If the update tax on medium is ~6 ms/step,
accum 4-8 puts the operating point at or past 0.40.

Grid: medium micro-4/5 at accum 2/4/8, plus small micro-16 accum 4 (the
BASELINE workload with the same trick).  All guarded; OOM fails the
stage only.
"""

import time

from probe_common import ProbeLedger, enable_compile_cache, measure_mfu

OUT = __file__.replace("tpu_probe15.py", "TPU_PROBE15_r05.jsonl")


def main() -> None:
    enable_compile_cache()
    led = ProbeLedger(OUT)
    if not led.claim_or_abort():
        return
    import jax.numpy as jnp

    nr = dict(remat=False, norm_remat=True)
    bf16 = jnp.bfloat16
    for tag, preset, micro, accum in (
            ("medium_m4_a2", "medium", 4, 2),
            ("medium_m4_a4", "medium", 4, 4),
            ("medium_m4_a8", "medium", 4, 8),
            ("medium_m5_a4", "medium", 5, 4),
            ("small_m16_a4", "small", 16, 4),
    ):
        led.guarded(f"mfu:{tag}")(measure_mfu)(
            led, tag, nr, micro * accum, blocks=(1024, 1024),
            mu_dtype=bf16, preset=preset, accum_steps=accum)

    led.emit("done", {"total_s": round(time.perf_counter() - led.t0, 1)})


if __name__ == "__main__":
    main()
