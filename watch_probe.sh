#!/bin/bash
# When probe_loop.sh lands tpu_probe results (the chip healed), chase
# them with the real benchmark immediately — a healthy grant window must
# not wait for round end.  One claimant at a time: bench runs only after
# the probe's claim has exited.
cd /root/repo
while true; do
    if [ -s probe_r04.out ] && ! pgrep -f tpu_probe.py > /dev/null; then
        echo "probe results landed $(date -u +%H:%M:%S); running bench" \
            >> watch_probe.log
        python bench.py > BENCH_live_r04.json 2>> watch_probe.log
        echo "bench rc=$? $(date -u +%H:%M:%S)" >> watch_probe.log
        python bench.py --rl > BENCH_live_rl_r04.json 2>> watch_probe.log
        break
    fi
    sleep 60
done
