"""Headline benchmark: GPT-2-small training throughput / MFU on one chip.

Mirrors the reference's Train parity methodology
(/root/reference/doc/source/ray-air/benchmarks.rst:178 — framework overhead
vs native loops): here the measured quantity is model FLOP utilization of the
framework's own train step (bf16, Pallas flash attention, AdamW).
`vs_baseline` is MFU / 0.40 — the BASELINE.json north-star target of 40% MFU
for GPT-2 training.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import jax
import jax.numpy as jnp
import optax

# bf16 peak TFLOP/s per chip by device kind (public spec sheets)
_PEAK_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5": 197.0,      # v5e ("v5 lite")
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v6e": 918.0,
    "TPU v6 lite": 918.0,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "")
    # longest prefix first so "TPU v5p" isn't shadowed by "TPU v5"
    for key, tf in sorted(_PEAK_TFLOPS.items(), key=lambda kv: -len(kv[0])):
        if kind.startswith(key):
            return tf * 1e12
    return 197.0 * 1e12  # conservative default


def main():
    from ray_tpu.models import (TransformerConfig, flops_per_token,
                                init_params, make_train_step)

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = TransformerConfig.gpt2("small")
        batch, seq, steps = 8, 1024, 20
    else:  # smoke-test shape for CPU runs of this script
        cfg = TransformerConfig.tiny()
        batch, seq, steps = 4, 128, 3

    params, _ = init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(3e-4, weight_decay=0.1)
    opt_state = opt.init(params)
    step = jax.jit(make_train_step(cfg, opt), donate_argnums=(0, 1))
    # lm_loss runs the model on the full token length — keep it equal to
    # seq so the flash kernel's 128-block alignment holds
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq),
                                0, cfg.vocab_size)
    batch_data = {"tokens": tokens}

    # warmup (compile + 2 steps)
    for _ in range(2):
        params, opt_state, metrics = step(params, opt_state, batch_data)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, metrics = step(params, opt_state, batch_data)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tok_s = steps * tokens_per_step / dt
    flops_tok = flops_per_token(cfg, seq)
    mfu = tok_s * flops_tok / _peak_flops(jax.devices()[0])
    print(json.dumps({
        "metric": "gpt2s_train_mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / 0.40, 4),
        "detail": {"tokens_per_s": round(tok_s, 1),
                   "step_ms": round(1000 * dt / steps, 2),
                   "backend": jax.default_backend()},
    }))


if __name__ == "__main__":
    main()
