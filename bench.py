"""Headline benchmark: GPT-2 training MFU on one chip (gpt2-medium,
microbatch-4 x accum-16 — the best measured GPT-2-family config; the
BASELINE north star is "Train GPT-2 >= 40% MFU", met at 0.42 on a
single v5e).  gpt2-small, the r1-r4 headline workload, rides along as
detail.scaling.small_m16_a8_s1024 for round-over-round continuity.

Mirrors the reference's Train parity methodology
(/root/reference/doc/source/ray-air/benchmarks.rst:178 — framework overhead
vs native loops) and its always-report harness discipline
(/root/reference/python/ray/_private/ray_perf.py:93-150): the measured
quantity is model FLOP utilization of the framework's own train step (bf16,
Pallas flash attention, AdamW).  ``vs_baseline`` is MFU / 0.40 — the
BASELINE.json north-star target of 40% MFU for GPT-2 training.

Robustness contract: this script ALWAYS prints exactly ONE json line
{"metric", "value", "unit", "vs_baseline"} and exits 0 unless the fallback
path itself is broken.  TPU backend init is retried (fresh subprocess each
time — a failed XLA client init poisons the process); after retries it
falls back to a CPU smoke run so a number is still recorded.
"""

import json
import os
import subprocess
import sys
import time
import traceback

_CHILD_FLAG = "_BENCH_CHILD"   # value: "tpu" or "cpu"
# ONE generous TPU attempt, no separate devices() probe: every extra
# claim/release cycle against the tunnelled chip is a wedge opportunity
# (a killed claimant blocks the next client init until the grant times
# out — observed as rc=124 attempt chains).  The attempt doubles as the
# probe; a FAST failure (backend UNAVAILABLE) earns one retry, a slow
# one (wedged/compiling past budget) goes straight to the CPU fallback.
_TPU_ATTEMPT_TIMEOUT = 1500
_TPU_FAST_FAIL_S = 240

# bf16 peak TFLOP/s per chip by device kind (public spec sheets)
_PEAK_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5": 197.0,      # v5e ("v5 lite")
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v6e": 918.0,
    "TPU v6 lite": 918.0,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "")
    # longest prefix first so "TPU v5p" isn't shadowed by "TPU v5"
    for key, tf in sorted(_PEAK_TFLOPS.items(), key=lambda kv: -len(kv[0])):
        if kind.startswith(key):
            return tf * 1e12
    return 197.0 * 1e12  # conservative default


_SYNC_CROSS_CHECKED = False


def timed_mfu_loop(step, params, opt_state, data, steps,
                   tokens_per_step, flops_tok, peak):
    """THE timing discipline, shared by the headline measurement, the
    scaling rows, and probe_common.measure_mfu (one copy — the r4/r5
    barrier fixes each had to be reasoned about per-copy before this).

    ``float(m["loss"])`` is the barrier: a scalar host readback is the
    only sync the axon relay cannot satisfy at remote enqueue
    (block_until_ready returns early there).  If async dispatch outran
    the device (non-physical MFU), re-times with a per-step sync.

    Once per process, the unsynced timing is cross-checked against a
    per-step-synced timing and the ratio logged (ADVICE r5): a partially
    async timing can inflate MFU while staying inside the 0<mfu<0.95
    physicality band, where the band-triggered retry never fires — the
    cross-check catches that regime and adopts the synced number.
    Returns ``(mfu, dt, params, opt_state)`` — params/opt_state are
    threaded through because ``step`` donates them.
    """
    global _SYNC_CROSS_CHECKED
    m = None

    def timed(sync_each: bool) -> float:
        nonlocal params, opt_state, m
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, m = step(params, opt_state, data)
            if sync_each:
                float(m["loss"])
        float(m["loss"])
        return time.perf_counter() - t0

    dt = timed(False)
    if not _SYNC_CROSS_CHECKED:
        _SYNC_CROSS_CHECKED = True
        dt_sync = timed(True)
        ratio = dt_sync / dt if dt > 0 else float("inf")
        print(f"[bench] sync cross-check: unsynced={dt:.3f}s "
              f"synced={dt_sync:.3f}s ratio={ratio:.3f}"
              + (" (adopting synced timing)" if ratio > 1.05 else ""),
              file=sys.stderr, flush=True)
        if ratio > 1.05:  # enqueue outran the device but stayed in-band
            dt = dt_sync
    mfu = steps * tokens_per_step / dt * flops_tok / peak
    if not (0.0 < mfu < 0.95):  # async dispatch outran the device
        dt = timed(True)
        mfu = steps * tokens_per_step / dt * flops_tok / peak
    return mfu, dt, params, opt_state


def _run_measurement() -> dict:
    """The actual benchmark body; assumes a working JAX backend."""
    t_start = time.perf_counter()

    def log(msg: str) -> None:
        # progress breadcrumbs land in the parent's captured stderr tail,
        # so a timed-out attempt shows WHERE it stalled
        print(f"[bench {time.perf_counter() - t_start:7.1f}s] {msg}",
              file=sys.stderr, flush=True)

    log("importing jax...")
    import jax
    import jax.numpy as jnp  # noqa: F401
    import optax

    # Persistent compilation cache: a re-run after a timed-out attempt
    # skips straight past whatever stage compiled before the budget ran
    # out.  (Harmless on CPU; crucial on the tunnelled chip where the
    # first compile has been observed eating the whole 1500 s budget.)
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_compile_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as exc:  # older jax: cache is an optimization, not a need
        log(f"compilation cache unavailable: {exc}")

    from ray_tpu.models import (TransformerConfig, flops_per_token,
                                init_params, make_train_step)

    log(f"backend={jax.default_backend()} devices={jax.devices()}")
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # Canary: compile + run ONE tiny-model step before committing to
        # the full GPT-2 compile.  If the chip/tunnel is unhealthy this
        # fails in seconds with a clear breadcrumb instead of burning the
        # whole budget; it also proves the claim is live and exercises
        # the same jit path the real measurement uses.
        log("canary: tiny-model compile+step...")
        # d_model=256/4 heads → head_dim 64, so the canary compiles the
        # SAME Pallas flash-attention path the real measurement uses
        # (default tiny() has head_dim 16, which _flash_ok rejects)
        _ccfg = TransformerConfig.tiny(d_model=256)
        _cp, _ = init_params(jax.random.PRNGKey(0), _ccfg)
        _copt = optax.adamw(3e-4)
        _cstep = jax.jit(make_train_step(_ccfg, _copt))
        _ctok = jax.random.randint(jax.random.PRNGKey(1), (2, 128),
                                   0, _ccfg.vocab_size)
        _cp2, _, _cm = _cstep(_cp, _copt.init(_cp), {"tokens": _ctok})
        float(_cm["loss"])
        del _cp, _cp2, _cm, _cstep
        log("canary ok")
        # remat=False: gpt2-small at b8/s1024 fits HBM without
        # rematerialization, and remat's recompute FLOPs are real work
        # the MFU numerator does not count (~25-30% of the step).
        # loss_chunk: never materialize the full [16, 1024, 50304] fp32
        # logits (3.2 GB) — one [16, 128, 50304] block at a time.
        # norm_remat + flash blocks 1024x1024 + batch 16 + bf16 Adam-mu:
        # the round-4 on-chip ablation winners (TPU_PROBE_r04.jsonl:
        # 0.297 base -> 0.319 norm_remat -> 0.333 whole-seq q blocks;
        # TPU_PROBE3_r04.jsonl: 0.345 b8 1024x1024 k blocks -> 0.3601
        # b16; TPU_PROBE5_r04.jsonl: 0.3686 with bf16 mu; b24 OOMs).
        os.environ.setdefault("RAY_TPU_FLASH_BLOCK_Q", "1024")
        os.environ.setdefault("RAY_TPU_FLASH_BLOCK_K", "1024")
        # The BASELINE north star is "Train GPT-2 >= 40% MFU" (on a
        # v4-32; this measures ONE v5e).  The headline is the best
        # measured GPT-2-family config: gpt2-MEDIUM, microbatch 4 x
        # accum 16 — in-step gradient accumulation keeps activations at
        # the microbatch while amortizing the Adam-moment HBM traffic,
        # the lever that broke the 16-GiB batch bound
        # (TPU_PROBE15/16_r05.jsonl: flat medium_b5 0.3865 batch-bound
        # -> m4_a8 0.4175 -> m4_a16 0.4235).  gpt2-small, the r1-r4
        # headline workload, stays as the continuity row in
        # detail.scaling (its best is 0.3798 = model-shape-bound).
        cfg = TransformerConfig.gpt2("medium", remat=False,
                                     loss_chunk=128, norm_remat=True)
        batch, seq, steps, accum = 64, 1024, 6, 16
    else:  # smoke-test shape for CPU runs of this script
        cfg = TransformerConfig.tiny()
        batch, seq, steps, accum = 4, 128, 3, 1

    params, _ = init_params(jax.random.PRNGKey(0), cfg)
    # bf16 first moments halve the Adam-mu HBM traffic: +0.009 MFU on
    # the v5e (TPU_PROBE5_r04.jsonl b16_kk_bf16mu 0.3686 vs 0.3601)
    opt = optax.adamw(3e-4, weight_decay=0.1, mu_dtype=jnp.bfloat16)
    opt_state = opt.init(params)
    # dispatch-profiler shim over the train step: the compile ledger
    # (recompiles, compile seconds, distinct shapes) rides the bench
    # detail.  sample_every is effectively off — only first-seen-shape
    # dispatches sync, so the measured MFU loop is never perturbed.
    from ray_tpu.util.device_profile import DispatchProfiler
    prof = DispatchProfiler(sample_every=10 ** 9)
    step = prof.wrap("train_step",
                     jax.jit(make_train_step(cfg, opt,
                                             accum_steps=accum),
                             donate_argnums=(0, 1)))
    # lm_loss runs the model on the full token length — keep it equal to
    # seq so the flash kernel's 128-block alignment holds
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq),
                                0, cfg.vocab_size)
    batch_data = {"tokens": tokens}

    # warmup (compile + 2 steps); float() is a hard device→host sync — the
    # tunnelled backend has been seen returning early from block_until_ready
    log("warmup: compiling + 2 steps...")
    for _ in range(2):
        params, opt_state, metrics = step(params, opt_state, batch_data)
    float(metrics["loss"])
    log(f"warmup done; measuring {steps} steps")

    tokens_per_step = batch * seq
    flops_tok = flops_per_token(cfg, seq)
    peak = _peak_flops(jax.devices()[0])
    mfu, dt, params, opt_state = timed_mfu_loop(
        step, params, opt_state, batch_data, steps, tokens_per_step,
        flops_tok, peak)
    tok_s = steps * tokens_per_step / dt
    prof.set_flops_per_token("train_step", flops_tok)
    prof.note_tokens("train_step", (2 + steps) * tokens_per_step)
    detail = {"tokens_per_s": round(tok_s, 1),
              "step_ms": round(1000 * dt / steps, 2),
              "batch": batch, "accum": accum,
              "backend": jax.default_backend(),
              # compile ledger: recompiles past the warmup shape mean
              # the step program is shape-unstable (every entry here is
              # one XLA compile paid at dispatch time)
              "train_profile": prof.snapshot(peak)}
    detail["model"] = "gpt2-medium(355M) m4_a16" if on_tpu else "tiny-smoke"
    result = {
        "metric": "gpt2_train_mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / 0.40, 4),
        "detail": detail,
    }
    if on_tpu:
        # SAFETY LINE before the extra validation work: the parent takes
        # the LAST parseable stdout line, and salvages this one from a
        # TimeoutExpired — a measured TPU headline must never be lost to
        # a slow kernel-validation stage.
        print(json.dumps(result), flush=True)
        # free HBM before the validation allocates its own tensors (the
        # naive seq-8192 reference materializes a ~2 GB score matrix)
        del params, opt_state, batch_data, step
        # Piggyback on-chip kernel validation inside the SAME claim
        # (one claim/release cycle per attempt is the wedge-safety
        # rule): flash fwd/bwd numerics vs reference, flash-vs-naive
        # step time at two sequence lengths.
        try:
            detail["kernels"] = _validate_kernels_on_chip(log)
        except Exception as exc:  # never sink the headline number
            detail["kernels"] = {"error": repr(exc)[:200]}
        # Scaling evidence rows (VERDICT r4 next #1/#2): gpt2-medium at
        # the same recipe sits HIGHER on the roofline than small (the
        # 0.40 target's multi-chip argument), and the long-context row
        # is the SP story's single-chip anchor.  Same claim, guarded.
        try:
            detail["scaling"] = _scaling_rows_on_chip(log)
        except Exception as exc:
            detail["scaling"] = {"error": repr(exc)[:200]}
    return result


def _scaling_rows_on_chip(log) -> dict:
    """The scaling evidence rows at the headline recipe (probe8/9/15
    r5 operating points): gpt2-MEDIUM with in-step grad accumulation
    CROSSES the 0.40 GPT-2 target on one chip (m4_a16 0.4235, probe16); the
    long-context row anchors the SP story (seq4096 0.3236, where naive
    attention OOMs outright — probe9)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import (TransformerConfig, flops_per_token,
                                init_params, make_train_step)
    rows = {}
    peak = _peak_flops(jax.devices()[0])
    for name, preset, batch, seq, accum in (
            ("small_m16_a8_s1024", "small", 128, 1024, 8),
            ("small_b4_s4096", "small", 4, 4096, 1),
            ("llama1b_bf16p_b4_dots", "llama1b", 4, 1024, 1)):
        log(f"scaling: {name} compiling...")
        if preset == "llama1b":
            # the llama family row (BASELINE config #4): 1.5B params,
            # bf16 params + dots remat (fp32+Adam is ~19 GB > HBM) —
            # 0.4769 MFU in TPU_PROBE18_r05.jsonl
            cfg = TransformerConfig.llama(
                "1b", max_seq_len=1024, remat="dots", norm_remat=True,
                loss_chunk=128, param_dtype=jnp.bfloat16)
        else:
            cfg = TransformerConfig.gpt2(preset, remat=False,
                                         loss_chunk=128, norm_remat=True,
                                         max_seq_len=max(1024, seq))
        params, _ = init_params(jax.random.PRNGKey(0), cfg)
        opt = optax.adamw(3e-4, weight_decay=0.1, mu_dtype=jnp.bfloat16)
        opt_state = opt.init(params)
        step = jax.jit(make_train_step(cfg, opt, accum_steps=accum),
                       donate_argnums=(0, 1))
        data = {"tokens": jax.random.randint(jax.random.PRNGKey(1),
                                             (batch, seq), 0,
                                             cfg.vocab_size)}
        for _ in range(2):
            params, opt_state, m = step(params, opt_state, data)
        float(m["loss"])
        steps = 12
        flops_tok = flops_per_token(cfg, seq)
        mfu, dt, params, opt_state = timed_mfu_loop(
            step, params, opt_state, data, steps, batch * seq,
            flops_tok, peak)
        rows[name] = {"mfu": round(mfu, 4),
                      "step_ms": round(1000 * dt / steps, 1),
                      "batch": batch, "accum": accum,
                      "tok_s": round(steps * batch * seq / dt)}
        log(f"scaling: {name} mfu={rows[name]['mfu']}")
        del params, opt_state, step, data, m
    return rows


def _validate_kernels_on_chip(log) -> dict:
    """Flash-attention on the MXU: numerics parity (fwd + grads) and
    measured speedup vs unfused attention (the round-2 verdict's ask:
    an untested-on-hardware kernel is a prototype, not a component).

    Measurement notes from the first live TPU session (round 3):
      * Both flash and naive attention run their dots through the MXU,
        which truncates fp32 inputs toward bf16 — absolute error vs an
        fp32 reference is therefore ~1e-2 for EITHER path.  Parity is
        judged against a ``precision=HIGHEST`` reference: the kernel
        passes if it is at least as close to it as unfused attention is
        (plus slack for its bf16 bwd dots).
      * The tunnelled chip elides repeated identical dispatches (20
        identical calls "run" in 0.01 ms) and adds ~4 ms per dispatch —
        so kernels are timed as a Python-level chain where each call's
        query is the previous output: distinct args defeat the dispatch
        cache and the data dependence forces real sequential execution.
        Each of the n calls still pays tunnel dispatch (pipelined), so
        the reported per-call times are upper bounds on kernel cost.
    """
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.attention import reference_attention
    from ray_tpu.ops.flash_attention import flash_attention

    out: dict = {}
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(k1, (1, 512, 8, 64), jnp.float32)
    k = jax.random.normal(k2, (1, 512, 8, 64), jnp.float32)
    v = jax.random.normal(k3, (1, 512, 8, 64), jnp.float32)
    log("kernels: flash fwd parity (vs HIGHEST-precision reference)...")
    f = jax.jit(lambda *a: flash_attention(*a, causal=True))
    r = jax.jit(lambda *a: reference_attention(*a, causal=True))

    def hi_fn(q, k, v):
        with jax.default_matmul_precision("highest"):
            return reference_attention(q, k, v, causal=True)

    ref_hi = jax.jit(hi_fn)(q, k, v)
    err = float(jnp.max(jnp.abs(f(q, k, v) - ref_hi)))
    err_naive = float(jnp.max(jnp.abs(r(q, k, v) - ref_hi)))
    out["fwd_max_abs_err"] = round(err, 7)
    out["fwd_naive_err"] = round(err_naive, 7)
    log("kernels: flash bwd parity...")
    gf = jax.jit(jax.grad(lambda *a: (flash_attention(
        *a, causal=True).astype(jnp.float32) ** 2).sum(), argnums=(0, 1, 2)))

    def ghi_fn(*a):
        with jax.default_matmul_precision("highest"):
            return (reference_attention(*a, causal=True) ** 2).sum()

    gr = jax.jit(jax.grad(ghi_fn, argnums=(0, 1, 2)))
    bwd_err = max(float(jnp.max(jnp.abs(a - b)))
                  for a, b in zip(gf(q, k, v), gr(q, k, v)))
    out["bwd_max_abs_err"] = round(bwd_err, 6)
    # MXU-honest pass bar: no worse than the unfused path's own bf16
    # truncation (fwd), bounded absolute grad error (bwd's ds/p dots are
    # deliberately bf16, same as public flash implementations)
    out["numerics_ok"] = bool(err <= max(2.0 * err_naive, 2e-4)
                              and bwd_err < 5e-2)

    def _chained_time(fn, q0, kb, vb, n=16) -> float:
        # chain each call's query through the previous output: distinct
        # args defeat the dispatch cache, the data dependence forces real
        # sequential execution, and pipelined dispatch amortizes the
        # tunnel's per-call latency.  (A lax.scan chain would amortize
        # harder still, but scanned pallas bodies were observed wedging
        # the remote compile helper for >10 min — not worth the risk in
        # the same claim as the headline.)
        # The barrier is a scalar HOST READBACK, not block_until_ready:
        # under the axon relay block_until_ready returns at remote
        # enqueue (probe11 r5 measured a 1024-token llama prefill at a
        # non-physical 1.8 ms through it), which is exactly why the r4
        # capture showed flash≈naive "parity" at seq2048 — both sides
        # were timed at the enqueue floor.
        fnj = jax.jit(fn)
        out = fnj(q0, kb, vb)
        float(jnp.max(out))                       # compile + warmup
        t0 = time.perf_counter()
        for _ in range(n):
            out = fnj(out, kb, vb)
        float(jnp.max(out))
        return (time.perf_counter() - t0) / n

    for seq in (2048, 8192):
        try:
            kq, kk, kv2 = jax.random.split(jax.random.PRNGKey(seq), 3)
            qb = jax.random.normal(kq, (1, seq, 8, 64), jnp.bfloat16)
            kb = jax.random.normal(kk, (1, seq, 8, 64), jnp.bfloat16)
            vb = jax.random.normal(kv2, (1, seq, 8, 64), jnp.bfloat16)
            log(f"kernels: timing seq={seq}...")
            t_flash = _chained_time(
                lambda *a: flash_attention(*a, causal=True), qb, kb, vb)
            t_naive = _chained_time(
                lambda *a: reference_attention(*a, causal=True), qb, kb, vb)
            out[f"seq{seq}_flash_ms"] = round(t_flash * 1e3, 3)
            out[f"seq{seq}_naive_ms"] = round(t_naive * 1e3, 3)
            out[f"seq{seq}_speedup"] = round(t_naive / max(t_flash,
                                                           1e-9), 2)
        except Exception as exc:   # e.g. naive seq-8192 OOM: partial
            out[f"seq{seq}_error"] = repr(exc)[:120]  # results still land
    return out


def _run_serve_measurement() -> dict:
    """Serve north star #5: generation TTFT + decode throughput through
    the FULL serving path — HTTP proxy → router → replica holding a KV
    cache (reference: /root/reference/doc/source/serve/performance.md:19
    documents its stack's serving latencies the same way).

    Runs on the CPU backend deliberately: a Serve worker holding the
    tunnelled TPU grant would wedge it when shutdown kills the worker
    (round-3 lesson), so the serving-path overhead is measured here and
    the model-side TPU prefill/decode cost is measured in tpu_probe.py's
    direct-generate stage — the end-to-end TPU TTFT is their sum.
    """
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4)
    serve.start()

    @serve.deployment(max_concurrent_queries=8)
    class Generator:
        def __init__(self):
            import jax.numpy as jnp

            from ray_tpu.models import TransformerConfig
            from ray_tpu.serve.decode_session import DecodeSessionCore
            self.core = DecodeSessionCore(
                TransformerConfig.tiny(max_seq_len=256,
                                       dtype=jnp.float32), max_len=256)

        def __call__(self, req):
            return self.core.handle(req)

    import requests
    serve.run(Generator.bind(), name="generate")
    addr = serve.api.http_address()
    prompt_len, decode_steps = 64, 16
    # keep-alive session: a real streaming client holds its connection,
    # so per-request TCP setup must not inflate the measured path
    http = requests.Session()

    def session(i: int):
        """→ (ttft_s, [per-token decode seconds])  — distinct prompts
        per session so no cache anywhere can fake the numbers."""
        prompt = [(7 * i + j) % 250 for j in range(prompt_len)]
        t0 = time.perf_counter()
        r = http.post(f"{addr}/generate",
                      json={"op": "start", "prompt": prompt},
                      timeout=180)
        ttft = time.perf_counter() - t0
        r.raise_for_status()
        sid = r.json()["sid"]
        per_tok = []
        for _ in range(decode_steps):
            t0 = time.perf_counter()
            http.post(f"{addr}/generate",
                      json={"op": "next", "sid": sid},
                      timeout=60).raise_for_status()
            per_tok.append(time.perf_counter() - t0)
        # release the KV cache (sessions are real replica memory)
        http.post(f"{addr}/generate", json={"op": "end", "sid": sid},
                  timeout=60)
        return ttft, per_tok

    session(0)                       # warmup: compiles prefill + decode
    ttfts, decodes = [], []
    for i in range(1, 21):
        ttft, per_tok = session(i)
        ttfts.append(ttft)
        decodes.extend(per_tok)
    import numpy as np
    p50 = float(np.percentile(ttfts, 50)) * 1e3
    p90 = float(np.percentile(ttfts, 90)) * 1e3
    dec_p50 = float(np.percentile(decodes, 50)) * 1e3
    streaming = _measure_concurrent_streaming(http, addr, prompt_len)
    serve.shutdown()
    ray_tpu.shutdown()
    return {
        "metric": "serve_gen_ttft_ms_p50", "value": round(p50, 2),
        "unit": "ms",
        # the serving path itself is the measured quantity; 100 ms is
        # the reference's own interactive-serving yardstick
        # (performance.md: "latencies ... under 100ms" for its proxy)
        "vs_baseline": round(100.0 / max(p50, 1e-6), 4),
        "detail": {"p90_ttft_ms": round(p90, 2),
                   "decode_ms_per_tok_p50": round(dec_p50, 2),
                   "decode_tok_s": round(1000.0 / max(dec_p50, 1e-6), 1),
                   "sessions": 20, "prompt_len": prompt_len,
                   "path": "http_proxy->router->replica",
                   "model": "transformer-tiny(cpu harness)",
                   "streaming": streaming,
                   "note": ("TPU model-side prefill/decode measured in "
                            "tpu_probe.py; end-to-end TPU TTFT ~= this "
                            "path overhead + that prefill; 'streaming' "
                            "is the continuous-batching SSE lane "
                            "(chunked next_chunk drains) at 1/4/8 "
                            "concurrent sessions")},
    }


def _measure_concurrent_streaming(http, addr: str,
                                  prompt_len: int) -> dict:
    """Continuous-batching serve benchmark: N concurrent SSE streams
    through `/generate/stream` (replica decode engine + chunked
    `next_chunk` drains + sid-sticky routing).  Reports per-N
    ``agg_tok_s`` (total tokens / wall) and ``stream_ms_per_tok_p50``
    (per-session wall per token) — the serve-side counterpart of the
    raw `llama1b_b8_scan` batched-decode headline."""
    import threading

    import numpy as np
    import requests
    max_new = 32

    def stream_one(i: int, out: dict) -> None:
        prompt = [(11 * i + j) % 250 for j in range(prompt_len)]
        tokens = 0
        t0 = time.perf_counter()
        with requests.post(f"{addr}/generate/stream",
                           json={"prompt": prompt,
                                 "max_new_tokens": max_new},
                           stream=True, timeout=300) as r:
            r.raise_for_status()
            for line in r.iter_lines():
                if line.startswith(b"data: ") and b'"token"' in line:
                    tokens += 1
        out[i] = (time.perf_counter() - t0, tokens)

    stream_one(0, {})                # warmup: engine slot-step compile
    result = {}
    for n in (1, 4, 8):
        out: dict = {}
        threads = [threading.Thread(target=stream_one, args=(i, out))
                   for i in range(n)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        total = sum(tok for _, tok in out.values())
        per_tok = [dur / max(tok, 1) for dur, tok in out.values()]
        result[f"s{n}"] = {
            "agg_tok_s": round(total / max(wall, 1e-9), 1),
            "stream_ms_per_tok_p50":
                round(float(np.percentile(per_tok, 50)) * 1e3, 2),
            "sessions": n, "tokens": total,
        }
    return result


def _run_spec_bench() -> dict:
    """`--spec-bench`: the PR-6 model-side serve optimisations, at the
    ENGINE level (DecodeSessionCore.handle, no cluster/HTTP) so the
    numbers isolate the data plane the optimisations live in.

    * ``spec_ab``: ms/tok for N concurrent streams with speculative
      decoding on vs off, asserting byte-identical output.  The draft
      is the target's FIRST LAYER and the target's second-layer output
      projections are zeroed — an exact distillation pair (the only way
      untrained weights admit a cheap high-acceptance draft; a random
      independent draft measures ~1% acceptance and a weight-shared
      draft pays full-size proposal compute).  Every measured FLOP is
      really executed: the target runs both layers, the draft one.  On
      chip the draft is a real small model, e.g. gpt2s for llama-1b.
      The win is 2 dispatches per 1..k accepted tokens vs 1 per token,
      plus the k-wide verify forward batching what k single steps
      would compute.
    * ``ttft_under_load``: a long-prompt session joins a saturated
      8-session batch; reports the joiner's TTFT and the worst stall it
      inflicts on incumbent streams, vs their steady chunk cadence —
      chunked admission bounds that stall at ~one chunk program.
    """
    import dataclasses
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import TransformerConfig, init_params
    from ray_tpu.serve.config import DecodeEngineConfig
    from ray_tpu.serve.decode_session import DecodeSessionCore

    cfg = TransformerConfig.tiny(max_seq_len=256, n_layers=4,
                                 attention_impl="reference",
                                 dtype=jnp.float32)
    params, _ = init_params(jax.random.PRNGKey(5), cfg)
    # distillation pair at a realistic 4:1 compute ratio: zero layers
    # 2-4's output projections (the layers still RUN — their residual
    # contribution is exactly 0), so the 1-layer draft slice computes
    # the same function at a quarter of the FLOPs and acceptance sits
    # near 1.0
    layers = dict(params["layers"])
    for key in ("wo", "w_out"):
        layers[key] = layers[key].at[1:].set(0.0)
    params = {**params, "layers": layers}
    draft_cfg = dataclasses.replace(cfg, n_layers=1)
    draft_params = {**params, "layers": jax.tree_util.tree_map(
        lambda x: x[:1], layers)}
    # prompt = exactly one [1, 32] chunk block and a long decode tail
    # (sessions run to cache cap): the A/B isolates the decode path —
    # admission cost is identical on both sides and measured separately
    # by ttft_under_load.  Token queues are deeper than the stream so
    # the engine never pauses and the timed window is pure engine
    # throughput (client drains happen after, untimed, for the parity
    # assertion — concurrent polling only adds equal GIL noise to both
    # sides).
    max_len, nsess = 224, 4
    prompts = [[(11 * i + j) % 250 for j in range(32)]
               for i in range(nsess)]

    def run_core(core):
        r = core.handle({"op": "start", "prompt": list(range(32))})
        while True:                   # warmup: compiles every program
            o = core.handle({"op": "next_chunk", "sid": r["sid"],
                             "max_tokens": 8, "timeout_s": 10.0})
            if o["tokens"] or o.get("done"):
                break
        core.handle({"op": "end", "sid": r["sid"]})
        time.sleep(0.2)
        rs = [core.handle({"op": "start", "prompt": p})
              for p in prompts]
        st0 = core.handle({"op": "stats"})["engine"]
        t0 = time.perf_counter()
        while core.handle({"op": "stats"})["engine"]["occupied_slots"]:
            time.sleep(0.005)
        wall = time.perf_counter() - t0
        st1 = core.handle({"op": "stats"})["engine"]
        outs = []
        for r in rs:
            toks = list(r["token"])
            while True:
                o = core.handle({"op": "next_chunk", "sid": r["sid"],
                                 "max_tokens": 256})
                toks += o["tokens"]
                if o["done"]:
                    break
            core.handle({"op": "end", "sid": r["sid"]})
            outs.append(toks)
        toks_decoded = max(1, st1["tokens"] - st0["tokens"])
        return wall / toks_decoded * 1e3, outs, st1

    k = 12
    core_off = DecodeSessionCore(
        cfg, max_len=max_len, seed=5, params=params,
        engine=DecodeEngineConfig(max_slots=nsess,
                                  token_queue_depth=256))
    core_on = DecodeSessionCore(
        cfg, max_len=max_len, seed=5, params=params,
        engine=DecodeEngineConfig(max_slots=nsess,
                                  token_queue_depth=256,
                                  spec_draft=(draft_cfg, draft_params),
                                  spec_k=k))
    # best-of-3 interleaved rounds: a fresh process carries
    # allocator/XLA warm-up noise and CPU scheduling jitter moves
    # single rounds by ±40%; the per-core minimum is stable
    ms_off, outs_off, _ = run_core(core_off)
    ms_on, outs_on, st = run_core(core_on)
    for _ in range(2):
        ms_off = min(ms_off, run_core(core_off)[0])
        ms_on = min(ms_on, run_core(core_on)[0])
    assert outs_on == outs_off, \
        "speculative decode changed the token stream"
    core_off.engine.shutdown()
    core_on.engine.shutdown()
    spec_ab = {
        "sessions": nsess,
        "tokens_per_stream": len(outs_on[0]), "spec_k": k,
        "spec_off_ms_per_tok": round(ms_off, 3),
        "spec_on_ms_per_tok": round(ms_on, 3),
        "speedup": round(ms_off / max(ms_on, 1e-9), 2),
        "ratio_on_over_off": round(ms_on / max(ms_off, 1e-9), 3),
        "ms_per_tok_is": "aggregate engine decode wall per token, "
                         "4 concurrent slots",
        "acceptance": st["spec"]["acceptance"],
        "draft": "exact-distillation pair: draft = target's first "
                 "layer, target's 2nd-layer output projections zeroed "
                 "(untrained harness weights admit no other cheap "
                 "high-acceptance draft; on chip: gpt2s drafts for "
                 "llama-1b)",
        "output_identical": True,
    }

    # ---- TTFT under load: join a saturated batch with a long prompt.
    # Incumbents get a deep cache (long runway) and the poller lanes
    # record chunk-arrival timestamps continuously, so the joiner's
    # admission lands mid-stream and its inflicted stall is readable
    # from the incumbents' inter-chunk gaps.
    chunk_tokens = 32
    incumbents, joiner_prompt_len = 8, 128
    cfg2 = TransformerConfig.tiny(max_seq_len=2048,
                                  attention_impl="reference",
                                  dtype=jnp.float32)
    core = DecodeSessionCore(
        cfg2, max_len=2048, seed=5,
        engine=DecodeEngineConfig(max_slots=incumbents + 1,
                                  prefill_chunk_tokens=chunk_tokens))
    # warm every program shape the measurement touches ([1,32] blocks +
    # [1,1] tail + the decode step) so the joiner's TTFT is admission,
    # not compilation
    w = core.handle({"op": "start",
                     "prompt": [(3 + j) % 250 for j in range(80)]})
    while True:
        o = core.handle({"op": "next_chunk", "sid": w["sid"],
                         "max_tokens": 8})
        if o["tokens"] or o.get("done"):
            break
    core.handle({"op": "end", "sid": w["sid"]})

    stop = threading.Event()
    arrivals = [[] for _ in range(incumbents)]   # chunk arrival stamps

    def incumbent(i):
        r = core.handle({"op": "start",
                         "prompt": [(7 * i + j) % 250
                                    for j in range(40)]})
        while not stop.is_set():
            o = core.handle({"op": "next_chunk", "sid": r["sid"],
                             "max_tokens": 4, "timeout_s": 5.0})
            if o.get("done") or "error" in o:
                break
            if o["tokens"]:
                arrivals[i].append(time.perf_counter())
        core.handle({"op": "end", "sid": r["sid"]})

    threads = [threading.Thread(target=incumbent, args=(i,))
               for i in range(incumbents)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and \
            any(len(lane) < 30 for lane in arrivals):
        time.sleep(0.05)              # all lanes streaming steadily
    t_join = time.perf_counter()
    r = core.handle({"op": "start",
                     "prompt": [(13 + j) % 250
                                for j in range(joiner_prompt_len)]})
    ttft_ms = (time.perf_counter() - t_join) * 1e3
    time.sleep(0.5)
    stop.set()
    core.handle({"op": "end", "sid": r["sid"]})
    for t in threads:
        t.join(timeout=30)
    core.engine.shutdown()

    pre_gaps, join_gaps = [], []
    join_end = t_join + ttft_ms / 1e3
    for lane in arrivals:
        for t0, t1 in zip(lane, lane[1:]):
            if t1 < t_join:
                pre_gaps.append(t1 - t0)
            elif t1 <= join_end + 0.25:
                join_gaps.append(t1 - t0)
    steady_ms = float(np.percentile(pre_gaps, 50)) * 1e3 \
        if pre_gaps else 0.0
    worst_ms = float(np.max(join_gaps)) * 1e3 if join_gaps else 0.0
    stall_ms = max(0.0, worst_ms - steady_ms)
    ttft_load = {
        "incumbents": incumbents,
        "joiner_prompt_len": joiner_prompt_len,
        "prefill_chunk_tokens": chunk_tokens,
        "joiner_ttft_ms": round(ttft_ms, 2),
        "incumbent_chunk_interval_ms_p50": round(steady_ms, 2),
        "incumbent_worst_gap_during_join_ms": round(worst_ms, 2),
        "joiner_inflicted_stall_ms": round(stall_ms, 2),
        "stall_lt_chunk_interval": bool(stall_ms < max(steady_ms, 1e-9)),
    }
    return {"spec_ab": spec_ab, "ttft_under_load": ttft_load}


def _spec_bench_main() -> None:
    """`python bench.py --spec-bench`: run the PR-6 measurements and
    merge them into SERVE_BENCH.json's detail (the headline serve
    record stays the full-path `--serve` run)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("RAY_TPU_DEVICE_BACKEND", "cpu")
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    try:
        result = _run_spec_bench()
    except Exception:
        result = {"error": traceback.format_exc(limit=3)}
    print(json.dumps(result))
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "SERVE_BENCH.json")
    try:
        with open(path) as f:
            ledger = json.load(f)
    except Exception:
        ledger = {"metric": "serve_gen_ttft_ms_p50", "detail": {}}
    ledger.setdefault("detail", {}).update(result)
    try:
        with open(path, "w") as f:
            json.dump(ledger, f)
    except OSError:
        pass


def _run_autoscale_bench() -> dict:
    """`--autoscale-bench`: bursty multi-tenant chat through the FULL
    path (HTTP SSE -> proxy -> prefix-affinity router -> autoscaled
    engine replicas).  Sessions share a long system prompt and join/
    leave in phases; the ledger records the replica-count-vs-load
    timeline (the autoscaler tracking the burst and draining back
    down), prefix-hit vs cold TTFT on a warm replica, and that every
    stream completed with zero user-visible errors — scale-downs drain
    via live-session migration, never drop."""
    import threading

    import requests

    import ray_tpu
    from ray_tpu import serve, state
    from ray_tpu.serve.config import AutoscalingConfig

    ray_tpu.init(num_cpus=8)
    serve.start()

    @serve.deployment(
        max_concurrent_queries=32,
        autoscaling_config=AutoscalingConfig(
            min_replicas=1, max_replicas=3,
            occupancy_high=0.7, occupancy_low=0.25,
            target_occupancy=0.6, trend_window_s=4.0,
            upscale_delay_s=0.0, downscale_delay_s=2.0))
    class Chat:
        def __init__(self):
            import jax.numpy as jnp

            from ray_tpu.models import TransformerConfig
            from ray_tpu.serve.config import DecodeEngineConfig
            from ray_tpu.serve.decode_session import DecodeSessionCore
            self.core = DecodeSessionCore(
                TransformerConfig.tiny(max_seq_len=512,
                                       attention_impl="reference",
                                       dtype=jnp.float32),
                max_len=512,
                engine=DecodeEngineConfig(
                    max_slots=2, token_queue_depth=4, max_waiting=32,
                    admission_timeout_s=180.0))

        def engine_stats(self):
            return self.core.handle({"op": "stats"})

        def __call__(self, req):
            return self.core.handle(req)

    serve.run(Chat.bind(), name="chat")
    addr = serve.api.http_address()
    system = [(13 * j) % 250 for j in range(320)]   # shared sys prompt

    live = {"n": 0}
    live_lock = threading.Lock()
    timeline = []
    stop_sampler = threading.Event()

    def sampler():
        while not stop_sampler.is_set():
            try:
                reps = serve.list_deployments()["chat"]["num_replicas"]
            except Exception:
                reps = -1
            with live_lock:
                n = live["n"]
            timeline.append({"t": round(time.perf_counter() - t_base, 2),
                             "replicas": reps, "live_sessions": n})
            stop_sampler.wait(0.5)

    errors = []

    def stream(i, tokens=120, pace=0.04, suffix=None):
        """One paced SSE chat turn; returns (ttft_s, tokens_seen)."""
        prompt = system + (suffix or [251, (i * 3) % 250, i % 250])
        with live_lock:
            live["n"] += 1
        try:
            t0 = time.perf_counter()
            ttft = None
            seen = 0
            with requests.post(
                    f"{addr}/chat/stream",
                    json={"prompt": prompt, "max_new_tokens": tokens},
                    stream=True, timeout=600) as r:
                if r.status_code != 200:
                    errors.append(f"s{i}: HTTP {r.status_code}")
                    return None, 0
                for line in r.iter_lines():
                    if not line.startswith(b"data: "):
                        continue
                    body = line[len(b"data: "):]
                    if body == b"[DONE]":
                        break
                    ev = json.loads(body)
                    if "error" in ev:
                        errors.append(f"s{i}: {ev['error']}")
                        break
                    if "token" in ev:
                        if ttft is None:
                            ttft = time.perf_counter() - t0
                        seen += 1
                        time.sleep(pace)   # paced client: session lives
            if seen < tokens:
                errors.append(f"s{i}: {seen}/{tokens} tokens")
            return ttft, seen
        finally:
            with live_lock:
                live["n"] -= 1

    t_base = time.perf_counter()
    sam = threading.Thread(target=sampler, daemon=True)
    sam.start()
    # phase 1 — single tenant (warms compiles; fleet stays at min)
    stream(0, tokens=30, pace=0.0)
    # phase 2 — burst: 8 tenants sharing the system prompt join inside
    # 2s; slots saturate, waiting depth climbs, the fleet must grow
    threads = []
    burst_ttfts = []

    def one(i):
        ttft, _ = stream(i, tokens=120, pace=0.04)
        if ttft is not None:
            burst_ttfts.append(ttft)
    for i in range(1, 9):
        th = threading.Thread(target=one, args=(i,))
        th.start()
        threads.append(th)
        time.sleep(0.25)
    for th in threads:
        th.join(timeout=600)
    peak = max((p["replicas"] for p in timeline), default=1)
    # phase 3 — idle: the fleet must drain back to min via the
    # retirement path (ticks come from the proxy's autoscale nudge)
    deadline = time.perf_counter() + 60
    final = peak
    while time.perf_counter() < deadline:
        try:
            final = serve.list_deployments()["chat"]["num_replicas"]
        except Exception:
            pass
        if final == 1:
            break
        time.sleep(0.5)
    # phase 4 — prefix-hit vs cold TTFT on the now-stable warm fleet
    # (measuring mid-retirement would fold scale-down sheds into the
    # numbers): seed one donor session with the system prompt, then
    # A-B streams whose only difference is whether their 320-token
    # prefix is resident in a slot
    cold_ttfts, hit_ttfts = [], []
    _sse_ttft(requests, addr, system + [250], 4)     # donor seed
    # hits first, back to back: each admission gathers the 320-token
    # prefix from its predecessor's slot (slots are LIFO-reused, so
    # interleaving colds here would evict the donor between hits)
    for i in range(3):
        th, _ = _sse_ttft(requests, addr, system + [252, i], 8)
        if th is not None:
            hit_ttfts.append(th)
    for i in range(3):
        # cold: a prompt sharing NOTHING with any resident prefix
        cold_prompt = [(97 * (i + 1) + j) % 250 for j in range(320)]
        tc, _ = _sse_ttft(requests, addr, cold_prompt + [i], 8)
        if tc is not None:
            cold_ttfts.append(tc)
    # prefix-cache hit accounting straight from the engines
    hits = reused = 0
    try:
        # engine stats are per replica and the handle load-balances:
        # sample several times and keep the busiest replica's counts
        # (a conservative floor on fleet-wide hits)
        h = serve.get_handle("chat")
        for _ in range(8):
            st = h.engine_stats.remote().result(timeout_s=30.0)
            eng = (st or {}).get("engine") or {}
            pfx = eng.get("prefix") or {}
            if pfx.get("applied_hits", 0) >= hits:
                hits = pfx.get("applied_hits", 0)
                reused = pfx.get("tokens_reused", 0)
    except Exception:
        pass
    stop_sampler.set()
    sam.join(timeout=5)
    # per-deployment occupancy series through the satellite API (the
    # same series the autoscale loop trended)
    series_pts = 0
    try:
        hist = state.metrics_history(
            name="ray_tpu_serve_engine_occupied_slots",
            deployment="chat", kind="gauges")
        series_pts = sum(len(v) for v in hist.get("series", {}).values())
    except Exception:
        pass
    serve.shutdown()
    ray_tpu.shutdown()
    import numpy as np
    med = (lambda xs: round(float(np.median(xs)) * 1e3, 1)
           if xs else None)
    return {
        "peak_replicas": peak, "final_replicas": final,
        "burst_sessions": 8, "errors": errors[:10],
        "zero_user_visible_errors": not errors,
        "burst_ttft_ms_p50": med(burst_ttfts),
        "cold_ttft_ms_p50": med(cold_ttfts),
        "prefix_hit_ttft_ms_p50": med(hit_ttfts),
        "prefix_applied_hits": hits,
        "prefix_tokens_reused": reused,
        "occupancy_series_points": series_pts,
        "timeline": timeline,
    }


def _sse_ttft(requests, addr, prompt, tokens):
    """TTFT of one unpaced SSE stream (helper for the cold/hit A-B)."""
    t0 = time.perf_counter()
    ttft = None
    seen = 0
    with requests.post(f"{addr}/chat/stream",
                       json={"prompt": prompt,
                             "max_new_tokens": tokens},
                       stream=True, timeout=300) as r:
        if r.status_code != 200:
            return None, 0
        for line in r.iter_lines():
            if not line.startswith(b"data: "):
                continue
            body = line[len(b"data: "):]
            if body == b"[DONE]":
                break
            ev = json.loads(body)
            if "token" in ev:
                if ttft is None:
                    ttft = time.perf_counter() - t0
                seen += 1
    return ttft, seen


def _autoscale_bench_main() -> None:
    """`python bench.py --autoscale-bench`: run the bursty multi-tenant
    scenario in a fresh child and merge an `autoscale` block into
    SERVE_BENCH.json."""
    try:
        proc = _spawn("autoscale")
        result = _extract_json_line(proc.stdout)
        if proc.returncode != 0 or result is None:
            result = {"error": (proc.stderr or "").strip()[-400:]}
    except Exception:
        result = {"error": traceback.format_exc(limit=3)}
    print(json.dumps(result))
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "SERVE_BENCH.json")
    try:
        with open(path) as f:
            ledger = json.load(f)
    except Exception:
        ledger = {"metric": "serve_gen_ttft_ms_p50", "detail": {}}
    ledger["autoscale"] = result
    try:
        with open(path, "w") as f:
            json.dump(ledger, f)
    except OSError:
        pass


def _run_rl_measurement() -> dict:
    """PPO env-steps/s on the local device mesh (BASELINE north star #3:
    100k env-steps/s).  Uses DDPPO — every device a learner, pmean grad
    sync — so the number scales with the mesh instead of one chip."""
    import jax

    from ray_tpu.rl import CartPole, DDPPOConfig

    n = len(jax.devices())
    algo = DDPPOConfig(env=CartPole, num_envs=64, rollout_length=128,
                       num_learners=n, lr=1e-3, seed=0).build()
    algo.train()                      # compile + warmup
    t0 = time.perf_counter()
    steps = 0
    iters = 0
    while time.perf_counter() - t0 < 10.0 or iters < 3:
        res = algo.train()
        steps += res["env_steps_this_iter"]
        iters += 1
    dt = time.perf_counter() - t0
    rate = steps / dt
    return {
        "metric": "ppo_env_steps_per_s", "value": round(rate, 1),
        "unit": "env_steps/s", "vs_baseline": round(rate / 100_000, 4),
        "detail": {"algo": "DDPPO", "num_learners": n, "iters": iters,
                   "backend": jax.default_backend(),
                   "episode_reward_mean":
                       round(res["episode_reward_mean"], 1)},
    }


def _child_main(mode: str) -> None:
    """Run one measurement attempt in this (fresh) process."""
    if mode == "rl":
        print(json.dumps(_run_rl_measurement()))
        return
    if mode == "serve":
        # defend in the CHILD too: serve workers must never hold the
        # tunnelled TPU grant (shutdown kills them → wedge)
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        os.environ["RAY_TPU_DEVICE_BACKEND"] = "cpu"
        print(json.dumps(_run_serve_measurement()))
        return
    if mode == "autoscale":
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        os.environ["RAY_TPU_DEVICE_BACKEND"] = "cpu"
        print(json.dumps(_run_autoscale_bench()))
        return
    if mode == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
    print(json.dumps(_run_measurement()))


def _spawn(mode: str) -> "subprocess.CompletedProcess":
    env = dict(os.environ)
    env[_CHILD_FLAG] = mode
    if mode in ("cpu", "serve", "autoscale"):
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["RAY_TPU_DEVICE_BACKEND"] = "cpu"
    elif mode == "rl":  # 8-device host mesh, TPU plugin bypassed
        env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                    "RAY_TPU_DEVICE_BACKEND": "cpu",
                    "XLA_FLAGS": (env.get("XLA_FLAGS", "") +
                                  " --xla_force_host_platform_device"
                                  "_count=8")})
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__)], env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        capture_output=True, text=True,
        timeout=_TPU_ATTEMPT_TIMEOUT if mode == "tpu" else 1800)


_CAPTURE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_TPU_CAPTURE.json")


def _git_head() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10).stdout.strip()
    except Exception:
        return "unknown"


def _record_capture(result: dict) -> None:
    """Persist a successful real-TPU headline so a later wedged claim
    cannot erase it (best-effort; never sinks the measurement).  Stamped
    with the commit it measured so a report from a different tree is
    visibly labeled as such."""
    try:
        rec = dict(result)
        rec["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           time.gmtime())
        rec["captured_at_commit"] = _git_head()
        with open(_CAPTURE_PATH, "w") as f:
            json.dump(rec, f)
    except OSError:
        pass


def _load_capture():
    try:
        with open(_CAPTURE_PATH) as f:
            rec = json.load(f)
        return rec if (rec.get("detail") or {}).get("backend") == "tpu" \
            else None
    except (OSError, ValueError):
        return None


def _attach_probe_evidence(result: dict) -> dict:
    """Fold the on-chip probe ledgers' RL and generation measurements
    into the headline's detail, so the single BENCH json line carries
    every north-star number measured on the real chip this round
    (best-effort; never sinks the headline)."""
    try:
        import glob
        import re
        here = os.path.dirname(os.path.abspath(__file__))
        best_rl, gens, serve, vision = None, {}, None, {}
        paths = glob.glob(os.path.join(here, "TPU_PROBE*_r*.jsonl"))
        # only the NEWEST round's ledgers: a stale prior-round number must
        # not mask a regression by riding into the current headline
        rounds = {}
        for p in paths:
            m = re.search(r"_r(\d+)\.jsonl$", p)
            if m:
                rounds.setdefault(int(m.group(1)), []).append(p)
        for path in sorted(rounds[max(rounds)]) if rounds else []:
            try:
                with open(path) as f:
                    lines = f.readlines()
            except OSError:
                continue
            for line in lines:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict):
                    continue
                stage = rec.get("stage", "")
                if stage.startswith("rl_ppo") or stage == "rl_tpu":
                    rate = rec.get("env_steps_per_s")
                    if rate and (best_rl is None
                                 or rate > best_rl["env_steps_per_s"]):
                        best_rl = {k: rec[k] for k in
                                   ("env_steps_per_s", "num_envs",
                                    "rollout", "reward", "algo", "env")
                                   if k in rec}
                elif stage == "gen" and "tag" in rec \
                        and "error" not in rec:
                    gens[rec["tag"]] = {
                        k: rec[k] for k in
                        ("prompt_len", "prefill_ms",
                         "decode_ms_per_tok", "decode_tok_s",
                         "batch", "new_tokens", "ms_per_tok",
                         "agg_tok_s") if k in rec}
                elif (rec.get("kind") in ("chunked_prefill_ttft",
                                          "decode")
                      and rec.get("synced") and "tag" in rec):
                    gens[rec["tag"]] = {
                        k: rec[k] for k in
                        ("prompt_len", "chunk", "first_ms",
                         "warm_ttft_ms", "ms_per_tok") if k in rec}
                elif stage == "serve_ttft" and "error" not in rec:
                    serve = serve or {}
                    serve.setdefault(rec.get("model", "model"),
                                     {}).update(
                        {k: rec[k] for k in
                         ("p50_ttft_ms", "p90_ttft_ms",
                          "decode_ms_per_tok_p50", "prompt_len",
                          "path", "non_composite") if k in rec})
                elif stage == "serve_stream" and "error" not in rec:
                    serve = serve or {}
                    serve.setdefault(rec.get("model", "model"),
                                     {}).update(
                        {k: rec[k] for k in
                         ("stream_ms_per_tok_p50", "stream_tok_s")
                         if k in rec})
                elif (rec.get("model") == "vit-b16"
                      and "error" not in rec and "tag" in rec):
                    vision[rec["tag"]] = {
                        k: rec[k] for k in
                        ("mfu", "images_per_s", "batch",
                         "ms_per_batch") if k in rec}
        detail = result.setdefault("detail", {})
        if best_rl is not None:
            best_rl["backend"] = "tpu"
            detail["rl_tpu"] = best_rl
        if gens:
            detail["gen_tpu"] = gens
        if serve is not None:
            detail["serve_tpu"] = serve
        if vision:
            detail["vision_tpu"] = vision
    except Exception:
        pass
    return result


def _extract_json_line(out: str):
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _rl_main() -> None:
    """`python bench.py --rl`: PPO env-steps/s on an 8-device CPU mesh
    (the TPU headline stays the default mode; this is north star #3)."""
    try:
        proc = _spawn("rl")
        result = _extract_json_line(proc.stdout)
        if proc.returncode == 0 and result is not None:
            print(json.dumps(result))
            return
        err = proc.stderr.strip()[-300:]
    except Exception:
        err = traceback.format_exc(limit=2)
    print(json.dumps({
        "metric": "ppo_env_steps_per_s", "value": 0.0,
        "unit": "env_steps/s", "vs_baseline": 0.0,
        "detail": {"error": err}}))


def _serve_main() -> None:
    """`python bench.py --serve`: generation TTFT/decode through the
    full serving path (north star #5); also records the result to
    SERVE_BENCH.json for the round ledger."""
    try:
        proc = _spawn("serve")
        result = _extract_json_line(proc.stdout)
        if proc.returncode == 0 and result is not None:
            # the measurement is the product; the ledger write is
            # best-effort and must never sink it
            print(json.dumps(result))
            try:
                with open(os.path.join(os.path.dirname(
                        os.path.abspath(__file__)), "SERVE_BENCH.json"),
                        "w") as f:
                    json.dump(result, f)
            except OSError:
                pass
            return
        err = proc.stderr.strip()[-300:]
    except Exception:
        err = traceback.format_exc(limit=2)
    print(json.dumps({
        "metric": "serve_gen_ttft_ms_p50", "value": 0.0,
        "unit": "ms", "vs_baseline": 0.0,
        "detail": {"error": err}}))


def _run_serve_breakdown() -> dict:
    """`--serve-breakdown`: streamed generation through the FULL path
    (HTTP proxy → router → replica continuous-batching engine) on the
    CPU harness, then reduce the data-plane flight instruments to the
    per-phase attribution table (`state.serve_breakdown`).  The product
    is the COVERAGE number: attributed phase seconds (queue, admission,
    prefill, decode_dispatch, stream_drain) over client-measured
    seconds (TTFT + ITL sums) — >= 0.9 means the instruments explain at
    least 90% of what streaming clients actually waited."""
    import ray_tpu
    from ray_tpu import serve, state

    ray_tpu.init(num_cpus=4)
    serve.start()

    @serve.deployment(max_concurrent_queries=8)
    class Generator:
        def __init__(self):
            import jax.numpy as jnp

            from ray_tpu.models import TransformerConfig
            from ray_tpu.serve.decode_session import DecodeSessionCore
            self.core = DecodeSessionCore(
                TransformerConfig.tiny(max_seq_len=256,
                                       dtype=jnp.float32), max_len=256)

        def __call__(self, req):
            return self.core.handle(req)

    import requests
    serve.run(Generator.bind(), name="generate")
    addr = serve.api.http_address()
    http = requests.Session()
    prompt_len, max_new, n_sessions = 48, 24, 12

    def stream_one(i: int) -> int:
        prompt = [(13 * i + j) % 250 for j in range(prompt_len)]
        n = 0
        with http.post(f"{addr}/generate/stream",
                       json={"prompt": prompt,
                             "max_new_tokens": max_new,
                             "tenant": f"bench-{i % 3}"},
                       stream=True, timeout=180) as r:
            r.raise_for_status()
            for line in r.iter_lines():
                if line.startswith(b"data: ") and b"token" in line:
                    n += 1
        return n

    stream_one(0)        # warmup: compiles the chunk + decode programs
    total = sum(stream_one(i) for i in range(1, n_sessions + 1))
    time.sleep(1.5)      # final engine push (0.5s cadence) + fold
    table = state.serve_breakdown()
    serve.shutdown()
    ray_tpu.shutdown()
    dep = (table.get("deployments") or {}).get("generate") or {}
    cov = dep.get("coverage") or 0.0
    return {
        "metric": "serve_breakdown_coverage",
        "value": round(cov, 4),
        "unit": "fraction_of_client_measured_serve_time",
        "vs_baseline": round(cov / 0.9, 4),   # 0.9 is the floor
        "detail": {"sessions": n_sessions,
                   "tokens_streamed": total,
                   "phases": table.get("phases"),
                   "deployments": table.get("deployments"),
                   "note": "coverage = attributed phase seconds / "
                           "(TTFT sum + ITL sum) measured at the "
                           "proxy; >= 0.9 is the acceptance bar"},
    }


def _serve_breakdown_main() -> None:
    """`python bench.py --serve-breakdown` (`make serve-breakdown`):
    run the attribution measurement inline on the CPU backend and
    write the table into SERVE_BENCH.json's top-level ``breakdown``
    block (the headline serve record stays the `--serve` run)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("RAY_TPU_DEVICE_BACKEND", "cpu")
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    try:
        result = _run_serve_breakdown()
    except Exception:
        result = {"metric": "serve_breakdown_coverage", "value": 0.0,
                  "unit": "fraction_of_client_measured_serve_time",
                  "vs_baseline": 0.0,
                  "detail": {"error": traceback.format_exc(limit=3)}}
    print(json.dumps(result))
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "SERVE_BENCH.json")
    try:
        with open(path) as f:
            ledger = json.load(f)
    except Exception:
        ledger = {"metric": "serve_gen_ttft_ms_p50", "detail": {}}
    ledger["breakdown"] = {
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"), **result}
    try:
        with open(path, "w") as f:
            json.dump(ledger, f, indent=1)
    except OSError:
        pass


def _attr_main() -> None:
    """`python bench.py --attr`: scripted control-plane wave (task burst
    + actor burst), then append the per-RPC attribution table — where
    controller/nodelet handler time went, WAL append/fsync cost, loop
    lag, scheduler wave stats — to the SCALE_r06 ledger.  This is the
    'before' snapshot ROADMAP item 4 demands: the same table re-run
    after the batching/sharding work proves where the serialization
    points moved."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import ray_tpu
    from ray_tpu import state

    n_tasks = int(os.environ.get("RAY_TPU_ATTR_TASKS", "20000"))
    n_actors = int(os.environ.get("RAY_TPU_ATTR_ACTORS", "200"))
    ray_tpu.init(num_cpus=8, object_store_memory=256 * 1024 * 1024)
    try:
        @ray_tpu.remote
        def noop():
            return None

        @ray_tpu.remote
        class Member:
            def ping(self):
                return 1

        ray_tpu.get([noop.remote() for _ in range(500)], timeout=120)
        t0 = time.perf_counter()
        ray_tpu.get([noop.remote() for _ in range(n_tasks)],
                    timeout=900.0)
        task_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        actors = [Member.remote() for _ in range(n_actors)]
        assert sum(ray_tpu.get([a.ping.remote() for a in actors],
                               timeout=900.0)) == n_actors
        actor_dt = time.perf_counter() - t0
        time.sleep(1.0)   # let history/trace flush ticks settle
        attr = state.rpc_attribution()
        ctl = attr.get("controller") or {}
        result = {
            "wave": {"tasks": n_tasks, "task_rate_per_s":
                     round(n_tasks / task_dt, 1),
                     "actors": n_actors, "actor_rate_per_s":
                     round(n_actors / actor_dt, 1)},
            "controller_ops": (ctl.get("ops") or [])[:15],
            "controller_top3_by_total_s":
                [r["op"] for r in (ctl.get("ops") or [])[:3]],
            "wal": ctl.get("wal"),
            "controller_loop_lag": ctl.get("loop_lag"),
            "nodes": {nid: (a.get("ops") or [])[:10]
                      for nid, a in (attr.get("nodes") or {}).items()},
        }
        for a in actors:
            ray_tpu.kill(a)
    finally:
        ray_tpu.shutdown()
    print(json.dumps({"metric": "control_plane_rpc_attr",
                      "value": result["wave"]["task_rate_per_s"],
                      "unit": "tasks/s", "detail": result}))
    # merge into the SCALE_r06 ledger (best effort; the table printed
    # above is the product)
    try:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "SCALE_r06.json")
        ledger = {}
        if os.path.exists(path):
            with open(path) as f:
                ledger = json.load(f)
        ledger.setdefault("round", 6)
        ledger.setdefault(
            "what", "control-plane scale round 6 ledger; rpc_attr_before"
            " is the PR-10 per-RPC attribution snapshot taken BEFORE the"
            " item-4 batching/sharding work")
        ledger["rpc_attr_before"] = {
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            **result}
        with open(path, "w") as f:
            json.dump(ledger, f, indent=1)
    except OSError:
        pass


def main() -> None:
    mode = os.environ.get(_CHILD_FLAG)
    if mode:
        _child_main(mode)
        return
    if "--rl" in sys.argv:
        _rl_main()
        return
    if "--serve" in sys.argv:
        _serve_main()
        return
    if "--spec-bench" in sys.argv:
        _spec_bench_main()
        return
    if "--autoscale-bench" in sys.argv:
        _autoscale_bench_main()
        return
    if "--serve-breakdown" in sys.argv:
        _serve_breakdown_main()
        return
    if "--attr" in sys.argv:
        _attr_main()
        return

    errors = []
    for attempt in range(2):
        t0 = time.perf_counter()
        try:
            proc = _spawn("tpu")
        except subprocess.TimeoutExpired as exc:
            # SALVAGE: the child prints a safety line as soon as the
            # headline is measured, BEFORE the kernel-validation stage —
            # a timeout there must not cost the TPU number.
            out = exc.stdout or b""
            if isinstance(out, bytes):
                out = out.decode(errors="replace")
            salvaged = _extract_json_line(out)
            if salvaged is not None and \
                    (salvaged.get("detail") or {}).get("backend") == "tpu":
                salvaged["detail"]["kernels"] = {
                    "error": "attempt timed out during kernel "
                             "validation; headline salvaged"}
                _record_capture(salvaged)
                print(json.dumps(_attach_probe_evidence(salvaged)))
                return
            # the child's stderr breadcrumbs say WHERE it stalled
            # (client init → relay wedged; post-backend → compile)
            tail = exc.stderr or b""
            if isinstance(tail, bytes):
                tail = tail.decode(errors="replace")
            tail = " | ".join(tail.strip().splitlines()[-4:])
            crumbs = tail[-400:] or \
                "(none - blocked before jax import finished)"
            errors.append(f"tpu attempt {attempt}: timeout after "
                          f"{_TPU_ATTEMPT_TIMEOUT}s; breadcrumbs: "
                          f"{crumbs}")
            break  # a killed slow attempt may have wedged the grant: stop
        result = _extract_json_line(proc.stdout)
        if proc.returncode == 0 and result is not None:
            backend = (result.get("detail") or {}).get("backend")
            if backend != "tpu":
                # soft TPU-init failure fell back to jax's CPU backend: a
                # smoke number must not masquerade as the TPU headline —
                # and it's the same transient class the retry exists for
                errors.append(f"tpu attempt {attempt}: ran on "
                              f"backend={backend!r}, rejecting")
                time.sleep(5)
                continue
            _record_capture(result)
            print(json.dumps(_attach_probe_evidence(result)))
            return
        dt = time.perf_counter() - t0
        errors.append(f"tpu attempt {attempt}: rc={proc.returncode} "
                      f"after {dt:.0f}s "
                      f"stderr={proc.stderr.strip()[-300:]}")
        if dt > _TPU_FAST_FAIL_S:
            break  # slow failure: retrying would just eat the round
        time.sleep(5)

    # The chip could not be claimed NOW (wedged grant / a live claimant
    # holding it) — but if THIS harness already measured the SAME code on
    # the real chip earlier, that capture is the round's honest TPU
    # number.  Report it, clearly labeled, instead of letting a CPU smoke
    # value become the number of record (the round-3 failure mode: one
    # wedged claim at report time erased a whole round's on-chip work).
    captured = _load_capture()
    if captured is not None:
        captured.setdefault("detail", {})
        captured["detail"]["source"] = (
            "prior live on-chip capture by this harness (see "
            "captured_at / captured_at_commit); chip claim unavailable "
            "at report time")
        captured["detail"]["report_commit"] = _git_head()
        captured["detail"]["report_time_tpu_errors"] = errors[-1:]
        print(json.dumps(_attach_probe_evidence(captured)))
        return

    try:
        proc = _spawn("cpu")
        result = _extract_json_line(proc.stdout)
        if proc.returncode == 0 and result is not None:
            result.setdefault("detail", {})["tpu_errors"] = errors[-1:]
            print(json.dumps(_attach_probe_evidence(result)))
            return
        errors.append(f"cpu fallback: rc={proc.returncode} "
                      f"stderr={proc.stderr.strip()[-300:]}")
    except Exception:
        errors.append(f"cpu fallback: {traceback.format_exc(limit=2)}")

    # Last resort: still one parseable JSON line, value 0.
    print(json.dumps({
        "metric": "gpt2_train_mfu", "value": 0.0,
        "unit": "fraction_of_peak", "vs_baseline": 0.0,
        "detail": {"backend": "none", "errors": errors[-3:]},
    }))


if __name__ == "__main__":
    main()
