"""Fourth staged on-chip probe — scanned-generation throughput.

Probe2 timed decode per-dispatch (one jit call per token), which on the
tunnelled chip pays ~4 ms dispatch latency per token.  The framework's
real serving path (`ray_tpu.models.generate.generate`) compiles prefill
+ a `lax.scan` of decode_step into ONE program, so a whole completion
costs one dispatch.  This probe measures that path — the honest
chip-side generation throughput — at batch 1 and batch 8.

Same discipline: ONE claim, guarded stages, fsync'd ledger, never kill.
"""

import json
import os
import time
import traceback

T0 = time.perf_counter()
OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "TPU_PROBE4_r04.jsonl")


def log(msg: str) -> None:
    print(f"[probe4 {time.perf_counter() - T0:7.1f}s] {msg}", flush=True)


def emit(stage: str, payload: dict) -> None:
    rec = {"stage": stage, "t": round(time.perf_counter() - T0, 1)}
    rec.update(payload)
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())
    log(f"{stage}: {payload}")


def guarded(stage):
    def deco(fn):
        def run(*a, **kw):
            try:
                return fn(*a, **kw)
            except Exception as exc:
                emit(stage, {"error": repr(exc)[:300],
                             "tb": traceback.format_exc(limit=3)[-400:]})
                return None
        return run
    return deco


def main() -> None:
    import jax
    import jax.numpy as jnp

    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_compile_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    from ray_tpu.models import TransformerConfig, init_params
    from ray_tpu.models.generate import generate

    backend = jax.default_backend()
    dev = jax.devices()[0]
    emit("env", {"backend": backend,
                 "device": getattr(dev, "device_kind", "?")})
    if backend != "tpu":
        emit("abort", {"reason": f"backend={backend}, not tpu"})
        return

    @guarded("canary")
    def canary():
        x = jnp.ones((1024, 1024), jnp.bfloat16)
        jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
        emit("canary", {"ok": True})
        return True

    if canary() is None:
        emit("abort", {"reason": "canary failed; claim unhealthy"})
        return

    def gen_scan(tag, cfg, batch, prompt_len, max_new):
        t_init = time.perf_counter()
        params, _ = init_params(jax.random.PRNGKey(0), cfg)
        params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if x.dtype == jnp.float32 else x, params)
        jax.block_until_ready(params)
        prompt = jax.random.randint(jax.random.PRNGKey(1),
                                    (batch, prompt_len), 0, cfg.vocab_size)
        # greedy (temperature=0) — sampling cost is negligible either way
        toks = generate(params, prompt, cfg=cfg, max_new_tokens=max_new,
                        temperature=0.0)
        jax.block_until_ready(toks)        # compile + warmup
        compile_s = time.perf_counter() - t_init
        t0 = time.perf_counter()
        n_calls = 3
        for i in range(n_calls):
            prompt_i = (prompt + i + 1) % cfg.vocab_size
            toks = generate(params, prompt_i, cfg=cfg,
                            max_new_tokens=max_new, temperature=0.0)
            jax.block_until_ready(toks)
        dt = (time.perf_counter() - t0) / n_calls
        emit("gen_scan", {
            "tag": tag, "batch": batch, "prompt_len": prompt_len,
            "max_new": max_new,
            "e2e_ms": round(dt * 1e3, 1),
            "decode_tok_s_per_seq": round(max_new / dt, 1),
            "decode_tok_s_total": round(batch * max_new / dt, 1),
            "compile_s": round(compile_s, 1)})
        del params, toks

    grids = (
        ("gpt2s b1", TransformerConfig.gpt2(
            "small", remat=False, attention_impl="reference"), 1, 256, 128),
        ("gpt2s b8", TransformerConfig.gpt2(
            "small", remat=False, attention_impl="reference"), 8, 256, 128),
        ("llama-tiny b1", TransformerConfig.llama(
            "tiny", max_seq_len=1024, remat=False,
            attention_impl="reference"), 1, 512, 128),
        ("llama-1b b1", TransformerConfig.llama(
            "1b", max_seq_len=1024, remat=False,
            attention_impl="reference"), 1, 512, 128),
        ("llama-1b b8", TransformerConfig.llama(
            "1b", max_seq_len=1024, remat=False,
            attention_impl="reference"), 8, 512, 128),
    )
    for tag, cfg, batch, plen, mnew in grids:
        guarded(f"gen_scan:{tag}")(gen_scan)(tag, cfg, batch, plen, mnew)

    emit("done", {"total_s": round(time.perf_counter() - T0, 1)})


if __name__ == "__main__":
    main()
