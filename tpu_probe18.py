"""Eighteenth staged on-chip probe — llama-family TRAIN MFU.

The campaign's train rows are all gpt2 (learned pos-emb, GELU,
layernorm, MHA); the llama architecture exercises different compute
paths — RoPE rotation, SwiGLU (3 mlp matmuls), RMSNorm, GQA flash
attention — and BASELINE config #4 names the llama family explicitly.
Memory walls on one 16 GiB chip: llama-1b fp32 params + Adam is ~19 GB
(cannot fit), so the grid measures (a) llama-1b with bf16 params +
dots remat at b2, and (b) a ~700M fp32 llama config (d1536 x 24L,
vocab 32k) at the gpt2-medium-class operating point, with and without
accumulation.

Uses bench.timed_mfu_loop (the shared honest-barrier discipline)
directly since probe_common.measure_mfu builds gpt2 presets only.
"""

import os
import time

from probe_common import ProbeLedger, enable_compile_cache

OUT = __file__.replace("tpu_probe18.py", "TPU_PROBE18_r05.jsonl")


def main() -> None:
    enable_compile_cache()
    led = ProbeLedger(OUT)
    if not led.claim_or_abort():
        return
    import jax
    import jax.numpy as jnp
    import optax

    from bench import _peak_flops, timed_mfu_loop
    from ray_tpu.models import (TransformerConfig, count_params,
                                flops_per_token, init_params,
                                make_train_step)

    os.environ["RAY_TPU_FLASH_BLOCK_Q"] = "1024"
    os.environ["RAY_TPU_FLASH_BLOCK_K"] = "1024"
    peak = _peak_flops(jax.devices()[0])

    def mfu_stage(tag, cfg, batch, accum=1, steps=8, seq=1024):
        t0 = time.perf_counter()
        params, _ = init_params(jax.random.PRNGKey(0), cfg)
        opt = optax.adamw(3e-4, weight_decay=0.1, mu_dtype=jnp.bfloat16)
        opt_state = opt.init(params)
        step = jax.jit(make_train_step(cfg, opt, accum_steps=accum),
                       donate_argnums=(0, 1))
        data = {"tokens": jax.random.randint(jax.random.PRNGKey(1),
                                             (batch, seq), 0,
                                             cfg.vocab_size)}
        for _ in range(2):
            params, opt_state, m = step(params, opt_state, data)
        float(m["loss"])
        compile_s = time.perf_counter() - t0
        mfu, dt, params, opt_state = timed_mfu_loop(
            step, params, opt_state, data, steps, batch * seq,
            flops_per_token(cfg, seq), peak)
        led.emit("mfu", {"tag": tag, "params_m":
                         round(count_params(cfg) / 1e6),
                         "batch": batch, "accum": accum, "seq": seq,
                         "mfu": round(mfu, 4),
                         "step_ms": round(1000 * dt / steps, 1),
                         "tok_s": round(steps * batch * seq / dt),
                         "compile_s": round(compile_s, 1)})

    # (a) real llama-1b: bf16 params (fp32+Adam is ~19 GB), dots remat
    cfg_1b = TransformerConfig.llama(
        "1b", max_seq_len=1024, remat="dots", norm_remat=True,
        loss_chunk=128, param_dtype=jnp.bfloat16)
    led.guarded("mfu:llama1b_bf16p_b2_dots")(mfu_stage)(
        "llama1b_bf16p_b2_dots", cfg_1b, 2)
    led.guarded("mfu:llama1b_bf16p_b4_dots")(mfu_stage)(
        "llama1b_bf16p_b4_dots", cfg_1b, 4)

    # (b) ~700M llama architecture, fp32 params, no remat (the
    # gpt2-medium-class operating point on the llama compute path)
    cfg_700 = TransformerConfig(
        vocab_size=32000, d_model=1536, n_layers=24, n_heads=12,
        n_kv_heads=4, d_ff=6144, max_seq_len=1024, pos_emb="rope",
        activation="swiglu", norm="rmsnorm", tie_embeddings=False,
        remat=False, norm_remat=True, loss_chunk=128)
    led.guarded("mfu:llama700m_b4")(mfu_stage)(
        "llama700m_b4", cfg_700, 4)
    led.guarded("mfu:llama700m_m4_a8")(mfu_stage)(
        "llama700m_m4_a8", cfg_700, 32, accum=8)

    led.emit("done", {"total_s": round(time.perf_counter() - led.t0, 1)})


if __name__ == "__main__":
    main()
