#!/bin/bash
# Round-5 campaign, stage E: queued on the serial flock; runs probe13
# (the remaining MFU cells: medium b5/b6 + chunk/seq variants, the two
# unexplored large cells).
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9

ok13 () {
    [ -f TPU_PROBE13_r05.jsonl ] \
        && grep '"stage": "mfu"' TPU_PROBE13_r05.jsonl \
           | grep -v '"error"' | grep -q 'medium_b'
}

tries=0
while [ $tries -lt 10 ]; do
    tries=$((tries+1))
    echo "=== probe13 attempt $tries $(date -u +%H:%M:%S) ===" >> probe13_r05.err
    python tpu_probe13.py >> probe13_r05.out 2>> probe13_r05.err
    if ok13; then
        echo "=== probe13 landed $(date -u +%H:%M:%S) ===" >> probe13_r05.err
        break
    fi
    if [ -f TPU_PROBE13_r05.jsonl ] && ! ok13; then
        mv TPU_PROBE13_r05.jsonl "TPU_PROBE13_r05.abort.$tries"
    fi
    sleep 240
done
echo "stage E done $(date -u +%H:%M:%S)" >> campaign_r05.log
