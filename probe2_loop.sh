#!/bin/bash
# Retry tpu_probe2.py until the tunnelled chip claim succeeds (wedged
# grants fail client init after ~1500s; healthy chips init in <1s).
# One claimant at a time, never killed — the round-3 wedge discipline.
cd /root/repo
for i in $(seq 1 40); do
    echo "=== attempt $i $(date -u +%H:%M:%S) ===" >> probe2_r04.err
    python tpu_probe2.py >> probe2_r04.out 2>> probe2_r04.err
    rc=$?
    # Success = the probe got past the env stage (backend really tpu and
    # at least the RL canary emitted something beyond env/abort).
    if [ -f TPU_PROBE2_r04.jsonl ] && grep -qv '"stage": "env"\|"stage": "abort"' TPU_PROBE2_r04.jsonl; then
        echo "=== probe2 produced results (rc=$rc), stopping ===" >> probe2_r04.err
        break
    fi
    # A wedged claim aborts with backend!=tpu or errors out; clear the
    # abort-only ledger so the next attempt starts a fresh file.
    if [ -f TPU_PROBE2_r04.jsonl ]; then
        mv TPU_PROBE2_r04.jsonl "TPU_PROBE2_r04.abort.$i" 2>/dev/null
    fi
    sleep 90
done
