#!/bin/bash
# stage T: probe22 (scanned-generation honest decode) then the final
# validation bench on the count-weighted-accum tree.
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9

ok22 () {
    [ -f TPU_PROBE22_r05.jsonl ] \
        && grep '"stage": "serve_ttft"' TPU_PROBE22_r05.jsonl \
           | grep -v '"error"' | grep -qv ERRNEVER
}

tries=0
while [ $tries -lt 6 ]; do
    tries=$((tries+1))
    echo "=== probe22 attempt $tries $(date -u +%H:%M:%S) ===" >> probe22_r05.err
    python tpu_probe22.py >> probe22_r05.out 2>> probe22_r05.err
    if ok22; then
        echo "=== probe22 landed $(date -u +%H:%M:%S) ===" >> probe22_r05.err
        break
    fi
    sleep 240
done

echo "=== stage T bench $(date -u +%H:%M:%S) ===" >> campaign_r05.log
python bench.py > BENCH_live_r05_interim.json 2>> campaign_r05.log
echo "stage T bench rc=$? $(date -u +%H:%M:%S)" >> campaign_r05.log
