"""Twenty-second staged on-chip probe — the FLAGSHIP model through the
full serving path (BASELINE config #5 is "Serve: Llama inference"):
llama-1b, bf16 params, CHUNKED prefill (the bounded-compile answer to
the r4 compile killer) hosted in a Serve replica ON the chip, measured
proxy → router → replica in one request path.  The round-4 number
was composite (CPU-harness path overhead + separately-measured TPU
prefill) because a Serve worker killed while holding the tunnelled
grant wedged it; round 5 made worker exit graceful for
accelerator-holding processes (worker_runtime.request_exit: SIGTERM /
exit-RPC run interpreter teardown so the axon client releases the
grant) and raised the nodelet SIGKILL escalation grace — this probe
exercises exactly that teardown.

Claim discipline: the REPLICA worker is the one chip claimant (the
driver/cluster processes never initialize a jax backend); the campaign
flock serializes the probe against other claimants.  Ledger rows:
  env        — replica-reported backend/device (not a driver claim)
  serve_ttft — p50/p90 TTFT ms + decode ms/tok through the full path
"""

import json
import os
import time

# the nodelet must give TPU-holding workers time to exit gracefully
os.environ.setdefault("RAY_TPU_WORKER_SHUTDOWN_GRACE_S", "30")
# driver-side safety: the probe main process must never claim the chip,
# so keep its own jax (if anything imports it) off the TPU.  Worker
# processes get a clean env via worker_env below.
os.environ.setdefault("RAY_TPU_TPU_AUTODETECT", "0")

from probe_common import ProbeLedger  # noqa: E402

OUT = __file__.replace("tpu_probe22.py", "TPU_PROBE22_r05.jsonl")


def main() -> None:
    led = ProbeLedger(OUT)
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4)
    serve.start()

    @serve.deployment(max_concurrent_queries=4)
    class Generator:
        def __init__(self):
            import jax
            import jax.numpy as jnp

            from ray_tpu.models import TransformerConfig
            from ray_tpu.serve.decode_session import DecodeSessionCore
            self.backend = jax.default_backend()
            self.device = getattr(jax.devices()[0], "device_kind", "?")
            dtype = jnp.bfloat16 if self.backend == "tpu" else jnp.float32
            self.core = DecodeSessionCore(
                TransformerConfig.llama(
                    "1b", max_seq_len=1280,
                    param_dtype=jnp.bfloat16, dtype=dtype),
                max_len=1280, prefill_chunk=256, max_sessions=4)

        def __call__(self, req):
            if req.get("op") == "env":
                return {"backend": self.backend, "device": self.device}
            return self.core.handle(req)

    import requests
    serve.run(Generator.bind(), name="generate")
    addr = serve.api.http_address()
    http = requests.Session()

    # the replica, not the driver, claims the chip: ask it what it got.
    # llama-1b replica __init__ takes minutes (param init + first
    # compiles); until it is ready the proxy answers with a non-JSON
    # error body — poll instead of trusting the first reply.
    env = None
    deadline = time.time() + 900
    while time.time() < deadline:
        try:
            r = http.post(f"{addr}/generate", json={"op": "env"},
                          timeout=600)
            if r.status_code == 200:
                env = r.json()
                break
        except Exception:
            pass
        time.sleep(5.0)
    if env is None:
        led.emit("abort", {"reason": "replica never became ready"})
        _teardown(serve, ray_tpu)
        return
    led.emit("env", env)
    if env.get("backend") != "tpu":
        led.emit("abort", {"reason": f"replica backend={env.get('backend')}"})
        _teardown(serve, ray_tpu)
        return

    prompt_len, decode_steps = 1024, 8

    def session(i: int):
        prompt = [(11 * i + j) % 250 for j in range(prompt_len)]
        t0 = time.perf_counter()
        r = http.post(f"{addr}/generate",
                      json={"op": "start", "prompt": prompt}, timeout=900)
        ttft = time.perf_counter() - t0
        r.raise_for_status()
        sid = r.json()["sid"]
        per_tok = []
        for _ in range(decode_steps):
            t0 = time.perf_counter()
            http.post(f"{addr}/generate", json={"op": "next", "sid": sid},
                      timeout=120).raise_for_status()
            per_tok.append(time.perf_counter() - t0)
        http.post(f"{addr}/generate", json={"op": "end", "sid": sid},
                  timeout=120)
        return ttft, per_tok

    led.log("warmup (compiles prefill+decode on chip)")
    t0 = time.perf_counter()
    session(0)
    led.emit("warmup", {"compile_s": round(time.perf_counter() - t0, 1)})

    ttfts, decodes = [], []
    for i in range(1, 13):
        ttft, per_tok = session(i)
        ttfts.append(ttft)
        decodes.extend(per_tok)
    import numpy as np
    led.emit("serve_ttft", {
        "p50_ttft_ms": round(float(np.percentile(ttfts, 50)) * 1e3, 2),
        "p90_ttft_ms": round(float(np.percentile(ttfts, 90)) * 1e3, 2),
        "decode_ms_per_tok_p50":
            round(float(np.percentile(decodes, 50)) * 1e3, 2),
        "sessions": 12, "prompt_len": prompt_len,
        "decode_steps": decode_steps,
        "path": "http_proxy->router->replica(llama-1b ON CHIP)",
        "model": "llama-1b bf16 prefill_chunk=256",
        "non_composite": True})
    _teardown(serve, ray_tpu)
    led.emit("done", {"teardown": "graceful"})


def _teardown(serve, ray_tpu) -> None:
    # graceful, ordered: drain → serve shutdown (exit RPC → replica runs
    # interpreter teardown, releasing the grant) → cluster shutdown
    serve.shutdown()
    time.sleep(5.0)    # let the replica's python exit fully
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
