#!/bin/bash
# stage Q: probe20 (scanned-generation honest decode) then the final
# validation bench on the count-weighted-accum tree.
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9

ok20 () {
    [ -f TPU_PROBE20_r05.jsonl ] \
        && grep '"stage": "mfu"' TPU_PROBE20_r05.jsonl \
           | grep -v '"error"' | grep -q vit_b
}

tries=0
while [ $tries -lt 6 ]; do
    tries=$((tries+1))
    echo "=== probe20 attempt $tries $(date -u +%H:%M:%S) ===" >> probe20_r05.err
    python tpu_probe20.py >> probe20_r05.out 2>> probe20_r05.err
    if ok20; then
        echo "=== probe20 landed $(date -u +%H:%M:%S) ===" >> probe20_r05.err
        break
    fi
    sleep 240
done

echo "=== stage Q bench $(date -u +%H:%M:%S) ===" >> campaign_r05.log
python bench.py > BENCH_live_r05_interim.json 2>> campaign_r05.log
echo "stage Q bench rc=$? $(date -u +%H:%M:%S)" >> campaign_r05.log
