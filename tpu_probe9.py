"""Ninth staged on-chip probe — long-context training MFU.

The flash kernel's measured speedup grows with sequence (1.03x at 2048,
2.13x at 8192 vs unfused) — this probe measures what that buys a FULL
train step: gpt2-small at seq 4096/8192 (learned pos table stretches),
with and without selective remat.  The long-context rows anchor the
SP/ring-attention story: single-chip flash first, ring across chips
when the sequence outgrows one HBM.

Uses the shared probe_common harness.  Same discipline: ONE claim,
guarded stages, fsync'd ledger, never kill.
"""

import time

from probe_common import ProbeLedger, enable_compile_cache, measure_mfu

OUT = __file__.replace("tpu_probe9.py", "TPU_PROBE9_r05.jsonl")


def main() -> None:
    enable_compile_cache()
    led = ProbeLedger(OUT)
    if not led.claim_or_abort():
        return
    import jax.numpy as jnp

    nr = dict(remat=False, norm_remat=True)
    dots = dict(remat="dots", norm_remat=True)
    bf16 = jnp.bfloat16
    naive = dict(nr, attention_impl="reference")
    for tag, kw, batch, seq in (
            # flash-vs-naive at identical configs (VERDICT r4 weak #3:
            # the seq2048 kernel microbench showed 1.03x parity — settle
            # it with train-step MFU on both impls at 2048 and 4096)
            ("b2_seq2048_flash", dict(nr, attention_impl="flash"), 2, 2048),
            ("b2_seq2048_naive", naive, 2, 2048),
            ("b2_seq4096", nr, 2, 4096),
            ("b2_seq4096_naive", naive, 2, 4096),
            ("b4_seq4096", nr, 4, 4096),
            ("b1_seq8192", nr, 1, 8192),
            ("b2_seq8192_dots", dots, 2, 8192),
    ):
        led.guarded(f"mfu:{tag}")(measure_mfu)(
            led, tag, kw, batch, seq=seq, blocks=(1024, 1024),
            mu_dtype=bf16)

    led.emit("done", {"total_s": round(time.perf_counter() - led.t0, 1)})


if __name__ == "__main__":
    main()
