#!/bin/bash
# Round-5 campaign, stage L: live bench validation of the new headline
# recipe (small accum4) + scaling rows (medium a8 crossing 0.40).
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9
echo "=== stage L bench $(date -u +%H:%M:%S) ===" >> campaign_r05.log
python bench.py > BENCH_live_r05_interim.json 2>> campaign_r05.log
echo "stage L bench rc=$? $(date -u +%H:%M:%S)" >> campaign_r05.log
