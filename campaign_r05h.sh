#!/bin/bash
# Round-5 campaign, stage H: queued on the serial flock; runs probe14
# (probe14 rerun for the naive-attention comparison rows).
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9

ok14b () {
    [ -f TPU_PROBE14_r05.jsonl ] \
        && grep '"stage": "mfu"' TPU_PROBE14_r05.jsonl \
           | grep -v '"error"' | grep -q 'naive_b'
}

tries=0
while [ $tries -lt 10 ]; do
    tries=$((tries+1))
    echo "=== probe14 attempt $tries $(date -u +%H:%M:%S) ===" >> probe14_r05.err
    python tpu_probe14.py >> probe14_r05.out 2>> probe14_r05.err
    if ok14b; then
        echo "=== probe14 landed $(date -u +%H:%M:%S) ===" >> probe14_r05.err
        break
    fi
    if [ -f TPU_PROBE14_r05.jsonl ] && ! ok14b; then
        mv TPU_PROBE14_r05.jsonl "TPU_PROBE14_r05.abort.$tries"
    fi
    sleep 240
done
echo "stage H done $(date -u +%H:%M:%S)" >> campaign_r05.log
