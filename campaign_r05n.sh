#!/bin/bash
# stage N: probe18 — llama-family train MFU.
# a live validation of the new gpt2-medium headline recipe.
cd /root/repo
exec 9>/tmp/tpu_campaign.lock
flock 9

ok18 () {
    [ -f TPU_PROBE18_r05.jsonl ] \
        && grep '"stage": "mfu"' TPU_PROBE18_r05.jsonl \
           | grep -qv '"error"'
}

tries=0
while [ $tries -lt 6 ]; do
    tries=$((tries+1))
    echo "=== probe18 attempt $tries $(date -u +%H:%M:%S) ===" >> probe18_r05.err
    python tpu_probe18.py >> probe18_r05.out 2>> probe18_r05.err
    if ok18; then
        echo "=== probe18 landed $(date -u +%H:%M:%S) ===" >> probe18_r05.err
        break
    fi
    sleep 240
done

echo "stage N done $(date -u +%H:%M:%S)" >> campaign_r05.log
