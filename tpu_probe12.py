"""Twelfth staged on-chip probe — pixel-env RL past the compile
ceiling.

Round-4's probe6 stalled at 128 conv envs: one rollout program
proportional to the full env batch killed the remote compile helper at
>=512 envs (SURVEY §9).  PPOConfig.env_chunk is the engineered answer
(lax.map of chunk-sized rollouts — XLA compiles ONE 128-env body no
matter the env count); this probe measures PixelPong conv-PPO at
512/1024/2048 envs through it, with the 128-env flat program as the
control row.

Uses the shared probe_common harness.  Same discipline: ONE claim,
guarded stages, fsync'd ledger, never kill.
"""

import time

from probe_common import ProbeLedger, enable_compile_cache

OUT = __file__.replace("tpu_probe12.py", "TPU_PROBE12_r05.jsonl")


def main() -> None:
    enable_compile_cache()
    led = ProbeLedger(OUT)
    if not led.claim_or_abort():
        return

    def ppo_pong(num_envs, rollout, env_chunk):
        from ray_tpu.rl import PixelPong, PPOConfig
        algo = PPOConfig(env=PixelPong, num_envs=num_envs,
                         rollout_length=rollout, env_chunk=env_chunk,
                         num_sgd_epochs=2, num_minibatches=4, lr=3e-4,
                         seed=0).build()
        t_c = time.perf_counter()
        algo.train()                      # compile + warmup
        compile_s = time.perf_counter() - t_c
        t0 = time.perf_counter()
        steps = 0
        iters = 0
        while time.perf_counter() - t0 < 8.0 or iters < 3:
            res = algo.train()
            steps += res["env_steps_this_iter"]
            iters += 1
        dt = time.perf_counter() - t0
        led.emit("rl_ppo_pixel", {
            "env": "PixelPong(conv)", "num_envs": num_envs,
            "rollout": rollout, "env_chunk": env_chunk,
            "env_steps_per_s": round(steps / dt, 1), "iters": iters,
            "compile_s": round(compile_s, 1),
            "reward": round(res["episode_reward_mean"], 2)})

    for ne, chunk in ((128, None), (512, 128), (1024, 128), (2048, 256)):
        led.guarded(f"rl_ppo_pixel:{ne}")(ppo_pong)(ne, 64, chunk)

    led.emit("done", {"total_s": round(time.perf_counter() - led.t0, 1)})


if __name__ == "__main__":
    main()
